"""Serving-fleet subsystem tests.

Pins the PR 7 tentpole guarantees: N shared-nothing replicas behind the
failover router score BITWISE-equal to a single engine, the circuit
breaker walks its full state machine (closed → open → half-open →
closed, re-open on a failed probe), EngineStopped is a distinct
retryable shutdown error and no future — engine- or router-level — is
ever left unresolved, and the deterministic TM_FAULTS request-plane
drills hold: killing 1 of 4 replicas under concurrent load loses zero
accepted requests (breaker opens, supervisor restarts, half-open probe
recovers), and a staged rollout of a fault-injected bad version rolls
the whole fleet back with zero client-visible errors.
"""
import json
import threading
import time

import numpy as np
import pytest

from serving_util import train_small_serving_model

from transmogrifai_tpu import Dataset
from transmogrifai_tpu.resilience import faults
from transmogrifai_tpu.resilience.faults import TransientFaultError
from transmogrifai_tpu.workflow import Workflow


def _train(seed: int):
    model, ds, _name = train_small_serving_model(seed)
    return model, ds


@pytest.fixture(scope="module")
def served():
    return _train(3)


@pytest.fixture(scope="module")
def served_v2():
    return _train(17)


def _slice(ds, n0, n1):
    return Dataset({k: ds.column(k)[n0:n1] for k in ds.column_names},
                   {k: ds.ftype(k) for k in ds.column_names})


def _fast_cfg(**overrides):
    """Drill-friendly thresholds: fast supervision/recovery, decisive
    rollout gates (floor well above this box's honest serving p99)."""
    from transmogrifai_tpu.serving import FleetConfig

    base = dict(replicas=4, supervise_s=0.05, breaker_open_s=0.3,
                restart_backoff_s=0.1, backoff_s=0.005,
                rollout_bake_s=3.0, rollout_min_requests=6,
                rollout_p99_floor_ms=60.0)
    base.update(overrides)
    return FleetConfig(**base)


def _wait_until(pred, timeout=15.0, interval=0.02, tick=None):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        if tick is not None:
            tick()
        time.sleep(interval)
    return pred()


# ---------------------------------------------------------------------------
# circuit breaker state machine (unit, fake clock)
# ---------------------------------------------------------------------------

def test_circuit_breaker_full_state_machine():
    """closed -> open (consecutive failures) -> half-open after open_s
    -> closed on probe success; and re-open on a failed probe."""
    from transmogrifai_tpu.serving import CircuitBreaker

    now = {"t": 0.0}
    events = []
    cb = CircuitBreaker(failure_threshold=3, open_s=1.0,
                        clock=lambda: now["t"],
                        on_transition=lambda a, b: events.append((a, b)))
    assert cb.state == "closed" and cb.allow()
    cb.record_success()
    cb.record_failure()
    cb.record_failure()
    assert cb.state == "closed"          # 2 consecutive < threshold
    cb.record_failure()
    assert cb.state == "open"
    assert not cb.allow()                # open: no traffic
    now["t"] = 0.5
    assert not cb.allow()                # still open
    now["t"] = 1.0
    assert cb.state == "half_open"
    assert cb.allow() == "probe"         # THE probe slot
    assert not cb.allow()                # only one probe in flight
    cb.record_failure(probe=True)        # probe failed
    assert cb.state == "open"            # re-opened, timer re-armed
    assert not cb.allow()
    now["t"] = 2.0
    assert cb.allow() == "probe"         # next probe
    cb.record_success(probe=True)
    assert cb.state == "closed"
    assert cb.allow()
    # consecutive-failure counter reset with the close: one failure
    # must not instantly re-trip
    cb.record_failure()
    assert cb.state == "closed"
    assert events == [("closed", "open"), ("open", "half_open"),
                      ("half_open", "open"), ("open", "half_open"),
                      ("half_open", "closed")]


def test_circuit_breaker_ratio_trip_and_force_open():
    from transmogrifai_tpu.serving import CircuitBreaker

    now = {"t": 0.0}
    cb = CircuitBreaker(failure_threshold=100, ratio_threshold=0.5,
                        window=10, min_volume=10, open_s=1.0,
                        clock=lambda: now["t"])
    # interleaved outcomes: consecutive counter never reaches 100, but
    # the window ratio crosses 0.5 once min_volume outcomes exist
    for _ in range(5):
        cb.record_success()
        cb.record_failure()
    assert cb.state == "open"            # 5/10 failures >= 0.5
    now["t"] = 1.0
    assert cb.allow()
    # a STALE success (a pre-open request completing late) must not
    # close a half-open breaker — only the reserved probe's outcome may
    cb.record_success()
    assert cb.state == "half_open"
    # a stale failure just records too: the probe slot stays reserved
    cb.record_failure()
    assert cb.state == "half_open"
    assert not cb.allow()                # the real probe is still out
    cb.record_success(probe=True)        # THE probe settles it
    assert cb.state == "closed"
    cb.force_open()                      # observed-dead shortcut
    assert cb.state == "open" and not cb.allow()
    with pytest.raises(ValueError):
        CircuitBreaker(ratio_threshold=0.0)
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)


def test_circuit_breaker_probe_slot_released_on_overload():
    """An overload outcome (QueueFull/DeadlineUnmeetable) on the single
    half-open probe must FREE the slot, not wedge the breaker: the
    router records no success/failure for backpressure, so without an
    explicit release the reserved slot would leave the replica
    permanently unroutable in exactly the overload regime that trips
    breakers in the first place."""
    from transmogrifai_tpu.serving import CircuitBreaker

    now = {"t": 0.0}
    cb = CircuitBreaker(failure_threshold=1, open_s=1.0,
                        clock=lambda: now["t"])
    cb.record_failure()
    assert cb.state == "open"
    now["t"] = 1.0
    assert cb.allow()                    # probe slot reserved
    assert not cb.allow()
    cb.release_probe()                   # probe hit a FULL queue
    assert cb.state == "half_open"       # no penalty, no close
    assert cb.allow() == "probe"         # slot free: probe again
    cb.record_success(probe=True)
    assert cb.state == "closed"
    cb.release_probe()                   # closed: no-op, still closed
    assert cb.state == "closed" and cb.allow()


def test_probe_overload_failover_does_not_wedge_breaker(served):
    """Integration: half-open probe dispatch that fails with
    backpressure leaves the breaker probe-able, and the request itself
    fails over to the healthy replica."""
    from transmogrifai_tpu.serving import (EngineConfig, FleetConfig,
                                           QueueFull, ServingFleet)

    model, ds = served
    cfg = FleetConfig(replicas=2, breaker_failures=1, breaker_open_s=0.05,
                      route_attempts=3, backoff_s=0.001, supervise_s=10.0)
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1), config=cfg,
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        bad = fleet.replica_handles()[0]
        bad.breaker.record_failure()            # trip: threshold 1
        assert bad.breaker.state == "open"
        time.sleep(0.06)                        # open_s elapses
        assert bad.breaker.state == "half_open"
        assert bad.breaker.allow()              # reserve the probe slot
        # simulate the router's overload handling on that probe (count
        # the fake as routed so the drain-at-exit ledger stays balanced)
        fleet.stats.note_routed()
        fleet.router._after_failure(
            _FakeRouted(), bad, QueueFull("full"))
        assert bad.breaker.state == "half_open"
        assert bad.breaker.allow() == "probe"   # NOT wedged
        bad.breaker.record_success(probe=True)
        assert bad.breaker.state == "closed"
        # the fleet still serves throughout
        out = fleet.score(_slice(ds, 0, 4), timeout=30)
        assert len(next(iter(out.values()))) == 4


class _FakeRouted:
    """Minimal _RoutedRequest stand-in for driving _after_failure."""
    def __init__(self, probe=True):
        from concurrent.futures import Future
        self.future = Future()
        self.attempt = 99               # at budget: resolve, don't retry
        self.deadline = None
        self.last_replica = None
        self.tried = set()
        self.seq = 0
        self.probe = probe              # holds the half-open probe slot
        self.trace = None               # telemetry: unsampled
        self.t_submit = 0.0
        self.t_attempt = 0.0
        self.resolved = False           # no resolution booked yet
        self.hedge_scheduled = False
        self.inflight = []


# ---------------------------------------------------------------------------
# FleetConfig: strict TM_FLEET_* parsing (same convention as TM_FAULTS)
# ---------------------------------------------------------------------------

def test_fleet_config_env_strict_typo_rejection():
    from transmogrifai_tpu.serving import FleetConfig

    cfg = FleetConfig.from_env({"TM_FLEET_BREAKER_FAILURES": "7",
                                "TM_FLEET_BREAKER_OPEN_S": "0.25",
                                "IRRELEVANT_VAR": "x"})
    assert cfg.breaker_failures == 7
    assert cfg.breaker_open_s == 0.25
    with pytest.raises(ValueError, match="unknown fleet env var"):
        FleetConfig.from_env({"TM_FLEET_BREAKER_FALURES": "7"})  # typo
    with pytest.raises(ValueError, match="bad value"):
        FleetConfig.from_env({"TM_FLEET_ROUTE_ATTEMPTS": "three"})
    with pytest.raises(ValueError):
        FleetConfig(replicas=0)
    # every knob validates AT CONFIG TIME, not deep in CircuitBreaker
    # after the N-replica cold start — and rollout_min_requests=0 would
    # silently disable the rollout health gate (instant vacuous pass)
    with pytest.raises(ValueError, match="rollout_min_requests"):
        FleetConfig.from_env({"TM_FLEET_ROLLOUT_MIN_REQUESTS": "0"})
    with pytest.raises(ValueError, match="breaker_ratio"):
        FleetConfig.from_env({"TM_FLEET_BREAKER_RATIO": "1.5"})
    with pytest.raises(ValueError, match="must be >= 1"):
        FleetConfig.from_env({"TM_FLEET_BREAKER_WINDOW": "0"})
    with pytest.raises(ValueError, match="supervise_s"):
        FleetConfig.from_env({"TM_FLEET_SUPERVISE_S": "-1"})   # busy-spin
    with pytest.raises(ValueError, match=">= 0"):
        FleetConfig.from_env({"TM_FLEET_BREAKER_OPEN_S": "-1"})
    # explicit overrides win over env
    cfg = FleetConfig.from_env({"TM_FLEET_REPLICAS": "2"}, replicas=5)
    assert cfg.replicas == 5


def test_serve_cli_rejects_typod_fleet_env(tmp_path, monkeypatch):
    """serve --engine must validate TM_FLEET_* strictly even when
    single-engine mode wins — a typo'd knob fails the deploy loudly."""
    from transmogrifai_tpu.cli import main as cli_main

    monkeypatch.setenv("TM_FLEET_BREAKER_FALURES", "7")     # typo
    with pytest.raises(ValueError, match="TM_FLEET_BREAKER_FALURES"):
        cli_main(["serve", "--model", str(tmp_path / "nope"),
                  "--input", str(tmp_path / "in.jsonl"),
                  "--output", str(tmp_path / "out.jsonl"), "--engine"])


# ---------------------------------------------------------------------------
# EngineStopped: distinct, retryable, and nothing left unresolved
# ---------------------------------------------------------------------------

def test_engine_stop_nondrain_fails_queued_with_engine_stopped(served):
    from transmogrifai_tpu.serving import (EngineClosed, EngineConfig,
                                           EngineStopped, ServingEngine)

    model, ds = served
    eng = ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1),
                        config=EngineConfig(max_wait_ms=200.0))
    eng._accepting = True            # queue BEFORE the dispatcher runs
    futs = [eng.submit(_slice(ds, 0, 4)) for _ in range(3)]
    eng.stop(drain=False)
    for f in futs:
        assert f.done()              # no future left unresolved
        exc = f.exception()
        assert isinstance(exc, EngineStopped)
        assert exc.retryable is True     # router classification hook
    # a LATE submit still gets the plain (non-retryable) EngineClosed
    with pytest.raises(EngineClosed) as ei:
        eng.submit(_slice(ds, 0, 4))
    assert not isinstance(ei.value, EngineStopped)


def test_fleet_stop_nondrain_resolves_every_routed_future(served):
    """Fleet shutdown with requests held mid-queue: every router-level
    future resolves — completed or failed with EngineStopped — and the
    submitted == resolved ledger balances. Nothing hangs, nothing is
    silently dropped."""
    from transmogrifai_tpu.serving import (EngineConfig, EngineStopped,
                                           ServingFleet)

    model, ds = served
    fleet = ServingFleet(model, replicas=2, buckets=(32,),
                         warm_sample=_slice(ds, 0, 1), config=_fast_cfg(
                             replicas=2),
                         engine_config=EngineConfig(max_wait_ms=60.0))
    fleet.start()
    gates = []
    for h in fleet.replica_handles():
        backend = h.engine.registry.get().backend
        gate = threading.Event()
        real_run = backend.run

        def slow_run(n, vals, _gate=gate, _real=real_run):
            _gate.wait(10.0)
            return _real(n, vals)

        backend.run = slow_run
        gates.append(gate)
    futs = [fleet.submit(_slice(ds, 0, 3)) for _ in range(8)]
    stopper = threading.Thread(
        target=lambda: fleet.stop(drain=False, timeout=1.0))
    stopper.start()
    time.sleep(0.2)
    for g in gates:
        g.set()                      # release any in-flight batch
    stopper.join(20.0)
    assert not stopper.is_alive()
    assert _wait_until(lambda: all(f.done() for f in futs), timeout=10.0)
    outcomes = {"ok": 0, "stopped": 0}
    for f in futs:
        exc = f.exception()
        if exc is None:
            outcomes["ok"] += 1
        else:
            assert isinstance(exc, EngineStopped), exc
            outcomes["stopped"] += 1
    st = fleet.stats.as_dict()
    assert st["routed"] == len(futs)
    assert st["completed"] + st["failed"] == len(futs)
    assert outcomes["ok"] == st["completed"]
    # a LATE submit gets the PLAIN non-retryable EngineClosed — only
    # requests accepted BEFORE shutdown carry the retryable
    # EngineStopped, or an outer layer would retry a stopped fleet
    from transmogrifai_tpu.serving import EngineClosed
    with pytest.raises(EngineClosed) as ei:
        fleet.submit(_slice(ds, 0, 2))
    assert not isinstance(ei.value, EngineStopped)


# ---------------------------------------------------------------------------
# tentpole: fleet-vs-single-engine bitwise equivalence, 16 threads
# ---------------------------------------------------------------------------

def test_drain_stop_flushes_backoff_parked_requests(served):
    """fleet.stop(drain=True) must COMPLETE a request parked in the
    router's failover-backoff heap — flushed to the still-live replicas
    before any engine closes — not fail it with EngineStopped: 'drain
    completes accepted work' includes the failover path."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    cfg = _fast_cfg(replicas=2, backoff_s=30.0)     # parks for good
    fleet = ServingFleet(model, replicas=2, buckets=(32,),
                         warm_sample=_slice(ds, 0, 1), config=cfg,
                         engine_config=EngineConfig(max_wait_ms=1.0)
                         ).start()
    fleet.score(_slice(ds, 0, 2), timeout=30)       # warm, pre-context
    with faults.active("serving.router.route:raise-transient:1"):
        fut = fleet.submit(_slice(ds, 0, 3))        # 1st in-context
        # route arrival: fails, parks ~30 s out
        assert _wait_until(lambda: fleet.router._delayed, timeout=5.0)
        fleet.stop(drain=True, timeout=10.0)        # drain = arrival 2
    assert fut.done()
    assert fut.exception() is None                  # served, not errored
    assert len(next(iter(fut.result().values()))) == 3


def test_fleet_16_threads_bitwise_equal_to_single_engine(served):
    """16 client threads through a 4-replica fleet: every caller gets
    exactly its own rows, bitwise-equal to solo scoring — replica count
    is a deployment knob, never a numerics knob — and the router really
    spread load across replicas."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    naive = model.compile_scoring()
    rng = np.random.default_rng(5)
    sizes = [int(s) for s in rng.integers(1, 60, size=16)]
    refs = [naive.score_arrays(_slice(ds, 0, s)) for s in sizes]

    with ServingFleet(model, replicas=4, buckets=(32, 64),
                      warm_sample=_slice(ds, 0, 1), config=_fast_cfg(),
                      engine_config=EngineConfig(max_wait_ms=2.0)
                      ) as fleet:
        results = [None] * len(sizes)
        errors = []

        def client(i, s):
            try:
                results[i] = fleet.score(_slice(ds, 0, s), timeout=60)
            except Exception as e:          # pragma: no cover - loud
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i, s))
                   for i, s in enumerate(sizes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for i, (ref, got) in enumerate(zip(refs, results)):
            assert set(ref) == set(got)
            for k in ref:
                assert np.array_equal(ref[k], got[k]), (i, sizes[i], k)
        st = fleet.status()
        assert st["fleet"]["completed"] == len(sizes)
        assert st["fleet"]["failed"] == 0
        # round-robin over the home set: more than one replica served
        assert len([c for c in st["fleet"]["dispatches"].values()
                    if c > 0]) >= 2


# ---------------------------------------------------------------------------
# placement: consistent hash
# ---------------------------------------------------------------------------

def test_rendezvous_placement_deterministic_and_spread():
    from transmogrifai_tpu.serving.router import rendezvous_order

    replicas = ["r0", "r1", "r2", "r3"]
    for key in ("v1", "v2", "champion", "2026-08-03"):
        a = rendezvous_order(key, replicas)
        b = rendezvous_order(key, list(reversed(replicas)))
        assert a == b                     # input order never matters
        assert sorted(a) == sorted(replicas)
    # different version keys spread their primary across the fleet
    firsts = {rendezvous_order(f"model-{i}", replicas)[0]
              for i in range(40)}
    assert len(firsts) >= 3
    # removing a replica keeps the others' RELATIVE order (the
    # consistent-hash property: only the lost replica's versions move)
    full = rendezvous_order("v1", replicas)
    without = rendezvous_order("v1", [r for r in replicas
                                      if r != full[0]])
    assert without == [r for r in full if r != full[0]]


# ---------------------------------------------------------------------------
# failover: re-dispatch on replica failure, breaker isolation
# ---------------------------------------------------------------------------

def test_failover_redispatches_and_breaker_isolates_bad_replica(served):
    """One replica's backend fails every batch with a transient error:
    every request still succeeds (failover), the bad replica's breaker
    opens after the consecutive-failure threshold, and subsequent
    traffic routes around it (dispatch counts freeze)."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    cfg = _fast_cfg(replicas=3, breaker_failures=3,
                    breaker_open_s=30.0)    # stays open for the test
    with ServingFleet(model, replicas=3, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1), config=cfg,
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        bad = fleet.replica_handles()[0]
        backend = bad.engine.registry.get().backend

        def failing_run(n, vals):
            raise TransientFaultError("injected backend failure")

        backend.run = failing_run
        for i in range(30):
            got = fleet.score(_slice(ds, 0, 3), timeout=60)
            assert next(iter(got.values())).shape[0] == 3
        st = fleet.status()
        assert st["fleet"]["failovers"] >= 1
        assert st["breakers"][bad.name]["state"] == "open"
        assert st["fleet"]["breaker_opens"] >= 1
        frozen = st["fleet"]["dispatches"].get(bad.name, 0)
        for _ in range(10):
            fleet.score(_slice(ds, 0, 3), timeout=60)
        st2 = fleet.status()
        # open breaker: not one more dispatch reached the bad replica
        assert st2["fleet"]["dispatches"].get(bad.name, 0) == frozen
        assert st2["fleet"]["failed"] == 0


def test_deadline_survives_failover(served):
    """A deadline-carrying request that fails over still completes
    inside its budget: the backoff sleep is clamped to the remaining
    budget instead of sleeping through it."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    cfg = _fast_cfg(replicas=2, backoff_s=5.0)   # un-clamped would blow
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1), config=cfg,
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        for _ in range(4):                       # seed both replicas' EMA
            fleet.score(_slice(ds, 0, 3), timeout=60)
        with faults.active("serving.engine.dispatch:raise-transient:1"):
            t0 = time.monotonic()
            got = fleet.score(_slice(ds, 0, 3), timeout=60,
                              deadline_ms=2000.0)
            elapsed = time.monotonic() - t0
            injected = faults.stats_dict()["injected"]
        assert next(iter(got.values())).shape[0] == 3
        assert elapsed < 2.0         # 5s backoff was deadline-clamped
        assert injected["serving.engine.dispatch:raise-transient"] == 1


# ---------------------------------------------------------------------------
# request-plane fault points
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_engine_dispatch_fault_point_recovers_via_failover(served):
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        with faults.active("serving.engine.dispatch:raise-transient:1"):
            got = fleet.score(_slice(ds, 0, 5), timeout=60)
        assert next(iter(got.values())).shape[0] == 5
        st = fleet.stats.as_dict()
        assert st["failovers"] >= 1
        assert st["failed"] == 0


@pytest.mark.faults
def test_router_route_fault_point_retries(served):
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        with faults.active("serving.router.route:raise-transient:1"):
            got = fleet.score(_slice(ds, 0, 5), timeout=60)
            assert faults.stats_dict()["injected"][
                "serving.router.route:raise-transient"] == 1
        assert next(iter(got.values())).shape[0] == 5
        assert fleet.stats.as_dict()["retries"] >= 1


# ---------------------------------------------------------------------------
# chaos drill: kill 1 of 4 replicas under concurrent load
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_chaos_kill_one_of_four_replicas_under_load(served):
    """The headline drill: TM_FAULTS kills a live replica mid-load.
    Every accepted request still completes (queued futures fail with
    EngineStopped and the router re-dispatches them), the dead
    replica's breaker opens, the supervisor restarts it, and the
    half-open probe closes the breaker — the fleet heals to full
    strength with zero client-visible errors."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    with ServingFleet(model, replicas=4, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1), config=_fast_cfg(),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        errors, ok = [], []
        lock = threading.Lock()

        def client(seed):
            rng = np.random.default_rng(seed)
            for _ in range(12):
                n = int(rng.integers(1, 12))
                try:
                    got = fleet.score(_slice(ds, 0, n), timeout=60)
                except Exception as e:      # pragma: no cover - loud
                    errors.append(e)
                    return
                with lock:
                    ok.append(n)

        # the 25th routed dispatch's replica dies, mid-load
        with faults.active("serving.replica.crash:raise-fatal:25"):
            threads = [threading.Thread(target=client, args=(s,))
                       for s in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            assert len(ok) == 8 * 12        # zero lost accepted requests
            assert faults.stats_dict()["injected"][
                "serving.replica.crash:raise-fatal"] == 1
            st = fleet.status()
            assert st["fleet"]["replica_crashes"] == 1
            assert st["fleet"]["breaker_opens"] >= 1

        # recovery: supervisor restart + half-open probe success. Keep
        # trickling traffic so the probe has something to ride.
        assert _wait_until(
            lambda: (fleet.stats.as_dict()["replica_restarts"] >= 1
                     and fleet.stats.as_dict()["breaker_closes"] >= 1),
            timeout=20.0,
            tick=lambda: fleet.score(_slice(ds, 0, 3), timeout=60))
        st = fleet.status()
        assert all(not h.dead and h.engine.live()
                   for h in fleet.replica_handles())
        assert all(b["state"] == "closed"
                   for b in st["breakers"].values())
        assert st["fleet"]["failed"] == 0
        # the engine-level ledger: every replica's counters reconcile
        # (nothing silently vanished inside any engine either)
        for name, rep in st["replicas"].items():
            e = rep["engine"]
            assert e["submitted"] == (e["completed"] + e["failed"]
                                      + e["shed_expired"]
                                      + e["cancelled"]), name


# ---------------------------------------------------------------------------
# staged rollout: success and auto-rollback drills
# ---------------------------------------------------------------------------

def test_staged_rollout_success_promotes_all_replicas(served, served_v2):
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model1, ds = served
    model2, _ = served_v2
    ref2 = model2.compile_scoring().score_arrays(_slice(ds, 0, 9))
    with ServingFleet(model1, replicas=3, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=3),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        stop = threading.Event()
        errors = []

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                try:
                    fleet.score(_slice(ds, 0, int(rng.integers(1, 10))),
                                timeout=60)
                except Exception as e:      # pragma: no cover - loud
                    errors.append(e)
                    return

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        # no buckets/warm_sample args: the rollout must INHERIT the
        # fleet's construction-time (32,) ladder, not reset to defaults
        report = fleet.rollout("v2", model2)
        stop.set()
        for t in threads:
            t.join()
        assert not errors
        assert report["rolled_back"] is False
        assert set(report["replicas"]) == {"r0", "r1", "r2"}
        st = fleet.status()
        assert st["default_version"] == "v2"
        assert st["fleet"]["rollouts"] == 1
        assert st["fleet"]["rollbacks"] == 0
        for rep in st["replicas"].values():
            assert rep["default_version"] == "v2"
            assert rep["versions"]["v1"]["retired"]      # old released
            assert rep["scoring"]["v2"]["buckets"] == [32]   # inherited
        (got,) = fleet.score(_slice(ds, 0, 9), timeout=60).values()
        (ref,) = ref2.values()
        assert np.array_equal(ref, got)                  # v2 serves


@pytest.mark.faults
def test_staged_rollout_bad_version_auto_rolls_back(served, served_v2):
    """The rollout drill: the candidate version is made pathologically
    slow by an injected dispatch hang (no errors — the nastiest
    regression to catch). The first baked replica's wait-p99 delta
    trips the monitor, the WHOLE fleet rolls back to v1, and clients
    saw zero errors throughout."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model1, ds = served
    model2, _ = served_v2
    ref1 = model1.compile_scoring().score_arrays(_slice(ds, 0, 9))
    with ServingFleet(model1, replicas=4, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1), config=_fast_cfg(),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        stop = threading.Event()
        errors = []

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                try:
                    fleet.score(_slice(ds, 0, int(rng.integers(1, 10))),
                                timeout=60)
                except Exception as e:      # pragma: no cover - loud
                    errors.append(e)
                    return

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        # every dispatch during the rollout drags 250 ms: far past the
        # 60 ms floor and 3x the baseline — deterministic regression
        with faults.active("serving.engine.dispatch:hang:1+:0.25"):
            report = fleet.rollout("v2", model2, buckets=(32,),
                                   warm_sample=_slice(ds, 0, 1))
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join()
        assert not errors                    # zero client-visible errors
        assert report["rolled_back"] is True
        assert "wait p99" in report["reason"]
        st = fleet.status()
        assert st["fleet"]["rollbacks"] == 1
        assert st["default_version"] == "v1"
        for rep in st["replicas"].values():
            assert rep["default_version"] == "v1"
            v2 = rep["versions"].get("v2")
            assert v2 is None or v2["retired"]   # bad version drained out
        (got,) = fleet.score(_slice(ds, 0, 9), timeout=60).values()
        (ref,) = ref1.values()
        assert np.array_equal(ref, got)          # v1 serves again
    assert fleet.stats.as_dict()["failed"] == 0


def test_fresh_fleet_rollout_skips_p99_gate_not_false_rollback(
        served, served_v2):
    """A rollout on a fleet with NO prior traffic has no latency
    baseline: the p99 gate must be skipped (there is no regression to
    measure), not judged as max(floor, 3 x 0.0) — which would
    false-rollback any healthy candidate whose honest under-load p99
    tops the floor. Error/shed gates still apply."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model1, ds = served
    model2, _ = served_v2
    cfg = _fast_cfg(replicas=2, rollout_bake_s=2.0,
                    rollout_min_requests=4,
                    rollout_p99_floor_ms=0.001)     # floor alone would trip
    with ServingFleet(model1, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1), config=cfg,
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        stop = threading.Event()
        errors = []

        def client():
            time.sleep(0.05)    # let the baseline read see ZERO history
            while not stop.is_set():
                try:
                    fleet.score(_slice(ds, 0, 3), timeout=60)
                except Exception as e:      # pragma: no cover - loud
                    errors.append(e)
                    return

        threads = [threading.Thread(target=client) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            # rollout IMMEDIATELY: no pre-rollout serving history
            report = fleet.rollout("v2", model2)
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors
        assert report["baseline"]["window_served"] == 0
        assert report["rolled_back"] is False, report["reason"]
        assert fleet.status()["default_version"] == "v2"


def test_rollout_baseline_is_recent_history_not_lifetime(served):
    """The baseline error rate comes from each replica's RECENT
    outcome-ring tail, not lifetime counters: a crash storm long before
    the rollout must not inflate the baseline until a candidate failing
    its bake would pass the error-rate gate."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model, ds = served
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        # an old storm: 50 lifetime failures on one replica...
        fleet.replica_handles()[0].engine.stats.note_failed(50)
        # ...then enough healthy traffic to refill every recent ring
        for _ in range(40):
            fleet.score(_slice(ds, 0, 2), timeout=30)
        base = fleet._recent_baseline(fleet.config.rollout_min_requests)
        lifetime = [h.engine.stats.outcome_counters()
                    for h in fleet.replica_handles()]
        lifetime_failed = sum(c["failed"] for c in lifetime)
        assert lifetime_failed >= 50          # the storm is on the books
        assert base["error_rate"] == 0.0      # but NOT in the baseline
        assert base["window_served"] > 0
        assert base["wait_p99_ms"] > 0.0


def test_concurrent_rollouts_rejected(served, served_v2):
    from transmogrifai_tpu.serving import ServingFleet

    model1, ds = served
    model2, _ = served_v2
    with ServingFleet(model1, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2)) as fleet:
        fleet._rollout_lock.acquire()
        try:
            with pytest.raises(RuntimeError, match="already in progress"):
                fleet.rollout("v2", model2, buckets=(32,))
        finally:
            fleet._rollout_lock.release()


# ---------------------------------------------------------------------------
# aggregated fleet /statusz + health endpoints
# ---------------------------------------------------------------------------

def test_fleet_status_aggregation_and_health_server(served):
    import urllib.error
    import urllib.request

    from transmogrifai_tpu.serving import HealthServer, ServingFleet

    model, ds = served
    fleet = ServingFleet(model, replicas=2, buckets=(32,),
                         warm_sample=_slice(ds, 0, 1),
                         config=_fast_cfg(replicas=2)).start()
    hs = HealthServer(fleet, port=0).start()
    base = f"http://127.0.0.1:{hs.port}"
    try:
        fleet.score(_slice(ds, 0, 5), timeout=60)
        st = fleet.status()
        # FleetStats ride the same snapshot_seq torn-read convention
        seq0 = st["fleet"]["snapshot_seq"]
        assert seq0 > 0
        assert st["fleet"]["dispatches"]
        assert set(st["breakers"]) == {"r0", "r1"}
        # per-replica snapshots carry the full per-engine EngineStats
        for rep in st["replicas"].values():
            assert rep["engine"]["snapshot_seq"] >= 0
            assert rep["supervision"]["alive"]
        fleet.score(_slice(ds, 0, 5), timeout=60)
        assert fleet.status()["fleet"]["snapshot_seq"] > seq0
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert json.loads(r.read())["live"] is True
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            assert json.loads(r.read())["ready"] is True
        with urllib.request.urlopen(f"{base}/statusz", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["replica_count"] == 2
        assert doc["fleet"]["completed"] == 2
        assert doc["config"]["replicas"] == 2
        fleet.stop()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/readyz", timeout=5)
        assert exc.value.code == 503
    finally:
        hs.stop()
        fleet.stop()


# ---------------------------------------------------------------------------
# CLI --engine --replicas mode
# ---------------------------------------------------------------------------

def test_serve_cli_fleet_mode(served, tmp_path):
    from transmogrifai_tpu.cli import main as cli_main

    model, ds = served
    model_dir = str(tmp_path / "model")
    model.save(model_dir)
    in_jsonl = str(tmp_path / "requests.jsonl")
    reqs = []
    with open(in_jsonl, "w") as f:
        for n in (1, 7, 3, 12, 5, 2):
            cols = {f"x{i}": [None if np.isnan(v) else float(v)
                              for v in ds.column(f"x{i}")[:n]]
                    for i in range(5)}
            reqs.append(n)
            f.write(json.dumps({"columns": cols}) + "\n")
    out_jsonl = str(tmp_path / "responses.jsonl")
    stats_json = str(tmp_path / "fleet_stats.json")
    rc = cli_main(["serve", "--model", model_dir, "--input", in_jsonl,
                   "--output", out_jsonl, "--engine", "--clients", "4",
                   "--replicas", "2", "--buckets", "32",
                   "--stats-json", stats_json])
    assert rc == 0
    with open(stats_json) as f:
        summary = json.load(f)
    assert summary["requests"] == len(reqs)
    assert summary["errors"] == 0
    # the status block is the AGGREGATED fleet snapshot
    assert summary["status"]["replica_count"] == 2
    assert summary["status"]["fleet"]["completed"] == len(reqs)
    naive = model.compile_scoring()
    pred_name = naive.result_names[0]
    with open(out_jsonl) as f:
        lines = [json.loads(l) for l in f]
    for i, n in enumerate(reqs):
        ref = naive.score_arrays(_slice(ds, 0, n))[pred_name]
        got = np.asarray(lines[i]["results"][pred_name])
        np.testing.assert_allclose(got, ref, atol=1e-6)


def test_serve_cli_fleet_mode_via_env(served, tmp_path, monkeypatch):
    """TM_FLEET_REPLICAS with no --replicas flag must pick fleet mode —
    a knob that parses fine but silently serves one unsupervised engine
    is exactly the failure the strict TM_FLEET_* convention forbids."""
    from transmogrifai_tpu.cli import main as cli_main

    model, ds = served
    model_dir = str(tmp_path / "model")
    model.save(model_dir)
    in_jsonl = str(tmp_path / "requests.jsonl")
    with open(in_jsonl, "w") as f:
        cols = {f"x{i}": [None if np.isnan(v) else float(v)
                          for v in ds.column(f"x{i}")[:4]]
                for i in range(5)}
        f.write(json.dumps({"columns": cols}) + "\n")
    out_jsonl = str(tmp_path / "responses.jsonl")
    stats_json = str(tmp_path / "fleet_stats.json")
    monkeypatch.setenv("TM_FLEET_REPLICAS", "2")
    rc = cli_main(["serve", "--model", model_dir, "--input", in_jsonl,
                   "--output", out_jsonl, "--engine", "--clients", "2",
                   "--buckets", "32", "--stats-json", stats_json])
    assert rc == 0
    with open(stats_json) as f:
        summary = json.load(f)
    assert summary["errors"] == 0
    assert summary["status"]["replica_count"] == 2      # fleet mode


# ---------------------------------------------------------------------------
# shared-nothing guard
# ---------------------------------------------------------------------------

def test_prebuilt_scorer_rejected_for_multi_replica(served):
    from transmogrifai_tpu.serving import ServingFleet

    model, ds = served
    scorer = model.compile_scoring(buckets=(32,))
    with pytest.raises(ValueError, match="shared-nothing"):
        ServingFleet(scorer, replicas=2)
    # fine for a single replica (degenerate fleet == one engine)
    fleet = ServingFleet(scorer, replicas=1, warm=False)
    assert len(fleet.replica_handles()) == 1
    # rollout enforces the SAME guard: rolling a prebuilt scorer out
    # would register one shared mutable backend behind every replica
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2)) as fleet2:
        with pytest.raises(ValueError, match="shared-nothing"):
            fleet2.rollout("v2", scorer)


def test_rollout_swap_failure_rolls_back_not_split_brain(served, served_v2):
    """A swap that RAISES on replica k (skew gate, exhausted load
    retries, a factory bug) must roll replicas 0..k-1 back to the old
    version and report — never strand the fleet split-brained with an
    exception flying at the caller."""
    from transmogrifai_tpu.serving import EngineConfig, ServingFleet

    model1, ds = served
    model2, _ = served_v2
    calls = {"n": 0}

    def factory():
        calls["n"] += 1
        if calls["n"] >= 2:             # r0 swaps clean, r1 dies
            raise RuntimeError("artifact load failed")
        return model2

    with ServingFleet(model1, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2, rollout_bake_s=0.2,
                                       rollout_min_requests=1),
                      engine_config=EngineConfig(max_wait_ms=1.0)
                      ) as fleet:
        fleet.score(_slice(ds, 0, 2), timeout=30)
        report = fleet.rollout("v2", factory, buckets=(32,),
                               warm_sample=_slice(ds, 0, 1))
        assert report["rolled_back"] is True
        assert "swap raised" in report["reason"]
        st = fleet.status()
        assert st["fleet"]["rollbacks"] == 1
        for rep in st["replicas"].values():
            assert rep["default_version"] == "v1"
            v2 = rep["versions"].get("v2")
            assert v2 is None or v2["retired"]
        out = fleet.score(_slice(ds, 0, 3), timeout=30)   # still serves
        assert len(next(iter(out.values()))) == 3


def test_cancelled_router_future_never_poisons_resolution(served):
    """Caller-side Future.cancel() racing the router's resolution must
    be swallowed (no InvalidStateError on the timer/dispatcher thread
    — that would strand every queued re-dispatch)."""
    from transmogrifai_tpu.serving import ServingFleet

    model, ds = served
    with ServingFleet(model, replicas=2, buckets=(32,),
                      warm_sample=_slice(ds, 0, 1),
                      config=_fast_cfg(replicas=2)) as fleet:
        req = _FakeRouted()
        fleet.stats.note_routed()
        req.future.cancel()
        fleet.router._resolve_error(req, RuntimeError("late error"))
        req2 = _FakeRouted()
        fleet.stats.note_routed()
        req2.future.cancel()
        fleet.router._resolve_result(req2, {"p": [1.0]})
        # neither resolution raised; both count as CANCELLED terminal
        # outcomes (so drain's ledger still balances at shutdown)
        out = fleet.score(_slice(ds, 0, 2), timeout=30)
        assert len(next(iter(out.values()))) == 2
        d = fleet.stats.as_dict()
        assert d["failed"] == 0
        assert d["cancelled"] == 2
