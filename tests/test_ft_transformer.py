"""FT-Transformer family tests (SURVEY §7 stretch selector candidate)."""
import numpy as np
import pytest

from transmogrifai_tpu.models.base import MODEL_FAMILIES

# full-suite tier: e2e/subprocess/training heavy (quick tier: -m 'not slow')
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def binary_data():
    rng = np.random.default_rng(0)
    n, d = 400, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    # nonlinear boundary a linear model cannot fully capture
    logit = 2.0 * X[:, 0] * X[:, 1] + X[:, 2]
    y = (logit + 0.3 * rng.normal(size=n) > 0).astype(np.float32)
    return X, y


def test_fit_beats_chance_on_nonlinear_boundary(binary_data):
    import jax.numpy as jnp
    from transmogrifai_tpu.evaluators.functional import auroc

    X, y = binary_data
    fam = MODEL_FAMILIES["FTTransformerClassifier"]
    hyper = {k: jnp.asarray(v, jnp.float32)
             for k, v in fam.default_hyper.items()}
    params = fam.fit_kernel(jnp.asarray(X), jnp.asarray(y),
                            jnp.ones(len(y), jnp.float32), hyper, 2)
    probs = np.asarray(fam.predict_kernel(params, jnp.asarray(X), 2))
    assert probs.shape == (len(y), 2)
    np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-5)
    a = float(auroc(jnp.asarray(probs[:, 1]), jnp.asarray(y), None))
    assert a > 0.85, a      # linear AUROC on this boundary is ~0.65


def test_grid_vmaps_and_fold_weights_differ(binary_data):
    import jax
    import jax.numpy as jnp

    X, y = binary_data
    fam = MODEL_FAMILIES["FTTransformerClassifier"]
    grid = [dict(fam.default_hyper, learningRate=1e-3),
            dict(fam.default_hyper, learningRate=1e-2)]
    hyper_b = fam.stack_grid(grid)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    w = jnp.ones(len(y), jnp.float32)

    def one(h):
        p = fam.fit_kernel(Xj, yj, w, h, 2)
        return fam.predict_kernel(p, Xj, 2)[:, 1]

    probs = np.asarray(jax.jit(jax.vmap(one))(hyper_b))
    assert probs.shape == (2, len(y))
    assert not np.allclose(probs[0], probs[1])  # lr changed the fit


def test_regression_family():
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    n, d = 300, 5
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] * X[:, 1] + 0.1 * rng.normal(size=n)).astype(np.float32)
    fam = MODEL_FAMILIES["FTTransformerRegressor"]
    hyper = {k: jnp.asarray(v, jnp.float32)
             for k, v in fam.default_hyper.items()}
    params = fam.fit_kernel(jnp.asarray(X), jnp.asarray(y),
                            jnp.ones(n, jnp.float32), hyper, 1)
    pred = np.asarray(fam.predict_kernel(params, jnp.asarray(X), 1))[:, 0]
    ss_res = float(((pred - y) ** 2).sum())
    ss_tot = float(((y - y.mean()) ** 2).sum())
    assert 1 - ss_res / ss_tot > 0.5    # linear R^2 on x0*x1 is ~0


def test_selector_candidate_and_persistence(binary_data, tmp_path):
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.dataset import Dataset
    from transmogrifai_tpu.features import types as ft
    from transmogrifai_tpu.models import BinaryClassificationModelSelector
    from transmogrifai_tpu.models.selector import ModelSelector
    from transmogrifai_tpu.workflow import Workflow, WorkflowModel

    X, y = binary_data
    # not a default candidate (expensive); explicit opt-in works
    assert "FTTransformerClassifier" not in \
        ModelSelector.default_candidates("binary")

    ds = Dataset({"v": X.astype(np.float32), "label": y.astype(np.float64)},
                 {"v": ft.OPVector, "label": ft.RealNN})
    label = FeatureBuilder.of(ft.RealNN, "label").from_column().as_response()
    vec = FeatureBuilder.of(ft.OPVector, "v").from_column().as_predictor()
    pred = BinaryClassificationModelSelector.with_train_validation_split(
        candidates=[["FTTransformerClassifier",
                     {"learningRate": [3e-3], "weightDecay": [1e-4]}]]
    ).set_input(label, vec).output
    model = Workflow([pred]).train(ds)
    best = model.selected_model().summary["bestModel"]
    assert best["family"] == "FTTransformerClassifier"

    scored = model.score(ds)
    p1 = np.asarray([r["probability_1"] for r in scored.column(pred.name)])
    model.save(str(tmp_path / "m"))
    m2 = WorkflowModel.load(str(tmp_path / "m"))
    p2 = np.asarray([r["probability_1"]
                     for r in m2.score(ds).column(pred.name)])
    np.testing.assert_allclose(p1, p2, atol=1e-6)


def test_ft_contributions_surface_in_insights(binary_data):
    import numpy as np
    from transmogrifai_tpu.insights import model_contributions
    from transmogrifai_tpu.models import OpFTTransformerClassifier
    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.dataset import Dataset
    from transmogrifai_tpu.features import types as ft

    X, y = binary_data
    ds = Dataset({"y": y.astype(np.float64), "v": X},
                 {"y": ft.RealNN, "v": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fx = FeatureBuilder.of(ft.OPVector, "v").from_column().as_predictor()
    model = OpFTTransformerClassifier().set_input(fy, fx).fit(ds)
    c = model_contributions(model)
    assert c is not None and c.shape == (X.shape[1],)
    assert np.all(c >= 0) and np.isfinite(c).all()


def test_ft_bf16_compute_quality(rng, monkeypatch):
    """TM_FT_BF16=1 runs the matmul forward in bf16 (norms/softmax/loss
    stay f32); the fitted model must remain predictive and close to the
    f32 fit's accuracy."""
    import jax.numpy as jnp
    import numpy as np

    from transmogrifai_tpu.models.base import MODEL_FAMILIES

    fam = MODEL_FAMILIES["FTTransformerClassifier"]
    old_steps = fam.n_steps
    fam.n_steps = 80
    try:
        n, d = 300, 6
        X = rng.normal(size=(n, d)).astype(np.float32)
        logit = 2.0 * X[:, 0] - X[:, 1]
        y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(np.float32)
        hyper = {k: jnp.asarray(v, jnp.float32)
                 for k, v in fam.default_hyper.items()}
        w = jnp.ones(n, jnp.float32)

        def acc(env_val):
            monkeypatch.setenv("TM_FT_BF16", env_val)
            p = fam.fit_kernel(jnp.asarray(X), jnp.asarray(y), w,
                               hyper, 2)
            probs = np.asarray(fam.predict_kernel(p, jnp.asarray(X), 2))
            return float(np.mean((probs[:, 1] > 0.5) == (y > 0.5)))

        a32 = acc("0")
        a16 = acc("1")
        assert a16 > 0.8
        assert abs(a16 - a32) < 0.08
    finally:
        fam.n_steps = old_steps
