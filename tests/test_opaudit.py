"""opaudit (transmogrifai_tpu.analysis) tests.

Three contracts pinned here:

1. **The tier-1 gate**: the full suite over the real tree reports ZERO
   unsuppressed findings, every suppression carries a reason, and the
   whole run fits the <15 s budget (one walk, one parse per file).
2. **No pass is vacuously green**: every pass catches a seeded
   violation in a synthetic fixture AND stays silent on the repaired
   version.
3. **The analyzer never executes analyzed code**: auditing a file
   whose import would raise at module scope succeeds.
"""
import json
import os
import subprocess
import sys
import time

import pytest

from transmogrifai_tpu.analysis import core
from transmogrifai_tpu.analysis import clones, concurrency, knobs, \
    locks, surfaces, trace_env

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ctx(tmp_path, files, docs=None):
    """In-memory AuditContext over synthetic sources (+ optional docs
    written under a tmp repo root)."""
    for rel, text in (docs or {}).items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(text)
    return core.AuditContext(
        str(tmp_path), [core.SourceFile(rel, text)
                        for rel, text in files.items()])


def _codes(findings):
    return [d.code for d in findings]


# ---------------------------------------------------------------------------
# 1. the tier-1 gate
# ---------------------------------------------------------------------------

def test_full_audit_zero_unsuppressed_findings_under_budget():
    """THE gate: the shipped tree audits clean. Any new invariant
    violation lands here as a failing tier-1 test with the pass name
    and fix hint in the message."""
    t0 = time.monotonic()
    report = core.run_audit(_REPO)
    elapsed = time.monotonic() - t0
    lint = report.pop("report")
    assert report["findings"] == [], "\n" + lint.format_text()
    # suppressed findings exist (the kernels trace-time policy block)
    # and every one of them was only accepted because its comment
    # carried a reason — reason-less ones surface as TM-AUDIT-310 above
    assert report["suppressed"], "expected reasoned suppressions"
    assert elapsed < 15.0, f"audit took {elapsed:.1f}s (budget 15s)"


def test_full_audit_json_report_is_deterministic():
    """Two runs -> byte-identical JSON (report ordering is pinned, no
    wall-clock or hash-order leakage)."""
    r1 = core.run_audit(_REPO)
    r2 = core.run_audit(_REPO)
    r1.pop("report")
    r2.pop("report")
    assert json.dumps(r1, sort_keys=True) == json.dumps(r2,
                                                        sort_keys=True)


def test_analyzer_never_imports_analyzed_code(tmp_path):
    """The never-executes pin: a module whose import raises at top
    level audits fine (pure ast.parse, nothing executed)."""
    evil = ("import os\n"
            "raise RuntimeError('imported — the audit executed me')\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/evil.py": evil})
    for fn in (trace_env.run, knobs.run_registry, locks.run_locks,
               locks.run_stats, clones.run, concurrency.run,
               core.suppression_findings):
        fn(ctx)                      # must not raise


@pytest.mark.slow
def test_cli_end_to_end_exit_codes(tmp_path):
    """python -m transmogrifai_tpu.analysis: exit 0 on the clean tree,
    JSON mode parseable, --changed-only filters to the listed files."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "transmogrifai_tpu.analysis", "--json"],
        capture_output=True, text=True, timeout=300, cwd=_REPO, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    doc = json.loads(r.stdout)
    assert doc["findings"] == []
    assert doc["files"] > 100
    r2 = subprocess.run(
        [sys.executable, "-m", "transmogrifai_tpu.analysis",
         "--changed-only", "transmogrifai_tpu/serving/engine.py"],
        capture_output=True, text=True, timeout=300, cwd=_REPO, env=env)
    assert r2.returncode == 0, r2.stdout[-2000:]


# ---------------------------------------------------------------------------
# 2. trace-env: seeded violation + repaired version
# ---------------------------------------------------------------------------

_TRACE_BAD = """\
import os
import jax

def policy():
    return os.environ.get("TM_FAKE_POLICY") == "1"

def kernel(x):
    if policy():
        return x + 1
    return x

def run(x):
    return jax.jit(kernel)(x)
"""

_TRACE_GOOD = """\
import os
import jax

def policy():
    return os.environ.get("TM_FAKE_POLICY") == "1"

def kernel(x, use_policy):
    if use_policy:
        return x + 1
    return x

def run(x):
    use_policy = policy()          # resolved OUTSIDE the trace
    import functools
    return jax.jit(functools.partial(kernel, use_policy=use_policy))(x)
"""


def test_trace_env_catches_env_read_reached_from_jit(tmp_path):
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": _TRACE_BAD})
    found = trace_env.run(ctx)
    assert "TM-AUDIT-301" in _codes(found)
    (d,) = [d for d in found if d.code == "TM-AUDIT-301"]
    assert "policy" in d.message and "kernel" in d.message


def test_trace_env_silent_on_resolved_argument_threading(tmp_path):
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": _TRACE_GOOD})
    assert trace_env.run(ctx) == []


def test_trace_env_resolves_package_init_reexports(tmp_path):
    """Relative imports INSIDE a package __init__ resolve against the
    package itself (not its parent), so a traced function reaching an
    env read through a `from .impl import helper` re-export is still
    caught — the false-negative class a one-level-too-deep strip
    silently creates."""
    files = {
        "transmogrifai_tpu/fakepkg/__init__.py":
            "from .impl import helper\n",
        "transmogrifai_tpu/fakepkg/impl.py":
            "import os\n"
            "def helper():\n"
            "    return os.environ.get('TM_FAKE_REEXPORT')\n",
        "transmogrifai_tpu/user.py":
            "import jax\n"
            "from .fakepkg import helper\n"
            "def kernel(x):\n"
            "    return x if helper() else -x\n"
            "def run(x):\n"
            "    return jax.jit(kernel)(x)\n",
    }
    ctx = _ctx(tmp_path, files)
    found = trace_env.run(ctx)
    assert any(d.location.startswith("transmogrifai_tpu/fakepkg/impl.py")
               for d in found), [d.message for d in found]


def test_trace_env_catches_decorated_and_module_global_forms(tmp_path):
    src = (
        "import os\n"
        "import jax\n"
        "_KNOB = os.environ.get('TM_FAKE_GLOBAL')\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x if _KNOB else -x\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake2.py": src})
    found = trace_env.run(ctx)
    assert any("_KNOB" in d.message for d in found)


# ---------------------------------------------------------------------------
# 2. knob-registry / knob-docs
# ---------------------------------------------------------------------------

_KNOB_BAD = "import os\nX = os.environ.get('TM_FAKE_RAW_KNOB')\n"
_KNOB_GOOD = (
    "from transmogrifai_tpu.resilience.config import parse_env_fields\n"
    "CATALOG = {'TM_FAKE_CAT_KNOB': ('field', int)}\n"
    "def load():\n"
    "    return parse_env_fields('TM_FAKE_CAT_KNOB', CATALOG)\n")


def test_knob_registry_flags_raw_read_and_accepts_catalog(tmp_path):
    bad = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": _KNOB_BAD})
    assert _codes(knobs.run_registry(bad)) == ["TM-AUDIT-302"]
    good = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": _KNOB_GOOD})
    assert knobs.run_registry(good) == []


def test_knob_docs_stale_then_regenerated(tmp_path):
    files = {"transmogrifai_tpu/fake.py": _KNOB_GOOD}
    ctx = _ctx(tmp_path, files)
    found = knobs.run_docs(ctx)
    assert _codes(found) == ["TM-AUDIT-303"]      # doc missing
    # regenerating repairs it
    ctx2 = _ctx(tmp_path, files,
                docs={knobs.KNOBS_DOC: ""})
    (tmp_path / knobs.KNOBS_DOC).write_text(
        knobs.render_knobs_doc(ctx2))
    ctx3 = _ctx(tmp_path, files)
    assert knobs.run_docs(ctx3) == []
    # and the generated table names the harvested knob
    assert "TM_FAKE_CAT_KNOB" in (tmp_path / knobs.KNOBS_DOC).read_text()


# ---------------------------------------------------------------------------
# 2. surface-registry (bench sections)
# ---------------------------------------------------------------------------

def _bench_src(sections, order, device, summary_names):
    summary = "".join(f"    x = results.get({n!r})\n"
                      for n in summary_names)
    return (
        "def a():\n    return {}\n\n"
        "_SECTIONS = {" + ", ".join(f"{n!r}: a" for n in sections)
        + "}\n"
        "_DEVICE_SECTIONS = frozenset({"
        + ", ".join(repr(n) for n in device) + "})\n"
        "_SECTION_ORDER = (" + ", ".join(repr(n) for n in order)
        + ("," if order else "") + ")\n\n"
        "def _summary_line(results, device_ok, complete, elapsed_s):\n"
        + (summary or "    pass\n") + "    return {}\n")


def _capture_src(priority):
    return ("PRIORITY = [" + ", ".join(repr(n) for n in priority)
            + "]\n")


def test_surface_registry_catches_each_drift_axis(tmp_path):
    ctx = _ctx(tmp_path, {
        surfaces.BENCH: _bench_src(
            sections=["s1", "s2", "s3"],
            order=["s1", "s2", "s2", "ghost"],    # s3 missing, dupe,
            device=["s2", "unknown"],             # ghost + unknowns
            summary_names=["s1", "s2"]),          # s3 invisible
        surfaces.CAPTURE: _capture_src(["s1"]),   # s2 (device) missing
    })
    msgs = [d.message for d in surfaces.run_sections(ctx)]
    assert any("'s3' in _SECTIONS but not _SECTION_ORDER" in m
               for m in msgs)
    assert any("schedules 's2' twice" in m for m in msgs)
    assert any("'ghost' is not a registered section" in m for m in msgs)
    assert any("_DEVICE_SECTIONS entry 'unknown'" in m for m in msgs)
    assert any("'s3' never appears in _summary_line" in m for m in msgs)
    assert any("device section 's2' missing from tpu_capture.PRIORITY"
               in m for m in msgs)


def test_surface_registry_silent_on_consistent_registries(tmp_path):
    ctx = _ctx(tmp_path, {
        surfaces.BENCH: _bench_src(
            sections=["s1", "s2"], order=["s1", "s2"], device=["s2"],
            summary_names=["s1", "s2"]),
        surfaces.CAPTURE: _capture_src(["s2"]),
    })
    assert surfaces.run_sections(ctx) == []


def test_surface_registry_guards_the_real_bench():
    """The real bench.py/tpu_capture.py audit clean — this is the test
    that REPLACES the hand-enumerated registry asserts test_bench.py
    used to carry (the enumeration now lives in the pass)."""
    ctx = core.load_context(_REPO)
    assert surfaces.run_sections(ctx) == []


# ---------------------------------------------------------------------------
# 2. fault-registry
# ---------------------------------------------------------------------------

_FAULTS_SRC = "POINTS = frozenset({'x.good', 'x.unused'})\n"
_FAULT_SITE = ("from transmogrifai_tpu.resilience.faults import "
               "fault_point\n\n"
               "def f():\n"
               "    fault_point('x.good')\n"
               "    fault_point('x.rogue')\n")


def test_fault_registry_catches_rogue_unused_and_undocumented(tmp_path):
    ctx = _ctx(tmp_path,
               {surfaces.FAULTS: _FAULTS_SRC,
                "transmogrifai_tpu/site.py": _FAULT_SITE},
               docs={surfaces.RESILIENCE_DOC: "| `x.good` | row |\n"})
    msgs = [d.message for d in surfaces.run_faults(ctx)]
    assert any("'x.rogue'" in m and "not catalogued" in m for m in msgs)
    assert any("'x.unused'" in m and "no source site" in m for m in msgs)
    assert any("'x.unused'" in m and "not documented" in m for m in msgs)
    assert not any("'x.good'" in m for m in msgs)


def test_fault_registry_silent_when_consistent(tmp_path):
    ctx = _ctx(tmp_path,
               {surfaces.FAULTS: "POINTS = frozenset({'x.good'})\n",
                "transmogrifai_tpu/site.py":
                    "def f():\n    fault_point('x.good')\n"},
               docs={surfaces.RESILIENCE_DOC: "| `x.good` | row |\n"})
    assert surfaces.run_faults(ctx) == []


# ---------------------------------------------------------------------------
# 2. metric-registry
# ---------------------------------------------------------------------------

_METRICS_BAD = (
    "_C = (('a', 'help a'), ('b', 'help b'))\n"
    "def emit(reg):\n"
    "    reg.counter('tm_fake_bad_counter', 'no _total suffix', 1)\n"
    "    for key, help_text in _C:\n"
    "        reg.counter(f'tm_fake_{key}_total', help_text, 1)\n")
_METRICS_GOOD = (
    "_C = (('a', 'help a'), ('b', 'help b'))\n"
    "def emit(reg):\n"
    "    reg.gauge('tm_fake_gauge', 'a gauge', 1)\n"
    "    for key, help_text in _C:\n"
    "        reg.counter(f'tm_fake_{key}_total', help_text, 1)\n")


def test_metric_registry_catches_bad_suffix_and_missing_doc(tmp_path):
    ctx = _ctx(tmp_path, {surfaces.METRICS: _METRICS_BAD},
               docs={surfaces.OBSERVABILITY_DOC: "no block here\n"})
    msgs = [d.message for d in surfaces.run_metrics(ctx)]
    assert any("tm_fake_bad_counter does not end _total" in m
               for m in msgs)
    assert any("no generated metric-registry block" in m for m in msgs)


def test_metric_registry_expands_fstrings_and_accepts_fresh_doc(
        tmp_path):
    files = {surfaces.METRICS: _METRICS_GOOD}
    ctx = _ctx(tmp_path, files)
    fams = {n for n, _t, _l in surfaces.emitted_families(
        ctx.file(surfaces.METRICS))}
    # static f-string expansion over the module constant
    assert {"tm_fake_a_total", "tm_fake_b_total",
            "tm_fake_gauge"} == fams
    block = surfaces.render_metric_registry(ctx)
    ctx2 = _ctx(tmp_path, files,
                docs={surfaces.OBSERVABILITY_DOC:
                      "# doc\n\n" + block + "\n"})
    assert surfaces.run_metrics(ctx2) == []


def test_metric_registry_guards_the_real_metrics_module():
    ctx = core.load_context(_REPO)
    assert surfaces.run_metrics(ctx) == []
    fams = {n for n, _t, _l in surfaces.emitted_families(
        ctx.file(surfaces.METRICS))}
    # the expansion really resolves the counter tables, not wildcards
    assert "tm_engine_submitted_total" in fams
    assert "tm_scaler_ticks_total" in fams


# ---------------------------------------------------------------------------
# 2. lock-discipline
# ---------------------------------------------------------------------------

_LOCK_CYCLE = (
    "import threading\n"
    "class A:\n"
    "    def __init__(self):\n"
    "        self._a_lock = threading.Lock()\n"
    "        self._b_lock = threading.Lock()\n"
    "    def one(self):\n"
    "        with self._a_lock:\n"
    "            with self._b_lock:\n"
    "                pass\n"
    "    def two(self):\n"
    "        with self._b_lock:\n"
    "            with self._a_lock:\n"
    "                pass\n")
_LOCK_OK = _LOCK_CYCLE.replace(
    "        with self._b_lock:\n"
    "            with self._a_lock:\n"
    "                pass\n",
    "        with self._a_lock:\n"
    "            with self._b_lock:\n"
    "                pass\n")
_LOCK_SELF = (
    "import threading\n"
    "class B:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "    def inner(self):\n"
    "        with self._lock:\n"
    "            pass\n"
    "    def outer(self):\n"
    "        with self._lock:\n"
    "            self.inner()\n")


def test_lock_discipline_catches_order_cycle(tmp_path):
    ctx = _ctx(tmp_path,
               {"transmogrifai_tpu/serving/fake.py": _LOCK_CYCLE})
    found = locks.run_locks(ctx)
    assert any("lock-order cycle" in d.message for d in found)


def test_lock_discipline_catches_nonreentrant_reacquire(tmp_path):
    ctx = _ctx(tmp_path,
               {"transmogrifai_tpu/serving/fake.py": _LOCK_SELF})
    found = locks.run_locks(ctx)
    assert any("self-deadlock" in d.message for d in found)


def test_lock_discipline_silent_on_consistent_order(tmp_path):
    ctx = _ctx(tmp_path,
               {"transmogrifai_tpu/serving/fake.py": _LOCK_OK})
    assert locks.run_locks(ctx) == []


def test_lock_discipline_real_serving_continuum_graph_acyclic():
    ctx = core.load_context(_REPO)
    assert locks.run_locks(ctx) == []


def test_lock_discipline_discovers_locks_by_kind_not_name(tmp_path):
    """``self._life = threading.Lock()`` is a lock even though 'lock'
    is not in the attribute name — the transport/worker naming the old
    name heuristic silently missed."""
    src = (
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._life = threading.Lock()\n"
        "    def inner(self):\n"
        "        with self._life:\n"
        "            pass\n"
        "    def outer(self):\n"
        "        with self._life:\n"
        "            self.inner()\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/serving/fake.py": src})
    found = locks.run_locks(ctx)
    assert any("self-deadlock" in d.message and "_life" in d.message
               for d in found), [d.message for d in found]


def test_lock_discipline_condition_is_reentrant_by_default(tmp_path):
    """A bare ``Condition()`` wraps an RLock — re-entering it is legal
    and must NOT flag (the ServingEngine._cond pattern)."""
    src = (
        "import threading\n"
        "class U:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "    def inner(self):\n"
        "        with self._cond:\n"
        "            pass\n"
        "    def outer(self):\n"
        "        with self._cond:\n"
        "            self.inner()\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/serving/fake.py": src})
    assert locks.run_locks(ctx) == []


def test_lock_discipline_condition_over_plain_lock_canonicalizes(
        tmp_path):
    """``Condition(self._x_lock)`` IS self._x_lock: nesting the
    condition inside a hold of the lock it wraps self-deadlocks when
    the wrapped lock is non-reentrant."""
    src = (
        "import threading\n"
        "class V:\n"
        "    def __init__(self):\n"
        "        self._x_lock = threading.Lock()\n"
        "        self._cond = threading.Condition(self._x_lock)\n"
        "    def bad(self):\n"
        "        with self._x_lock:\n"
        "            with self._cond:\n"
        "                pass\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/serving/fake.py": src})
    found = locks.run_locks(ctx)
    assert any("re-acquires" in d.message for d in found), \
        [d.message for d in found]


def test_lock_discipline_resolves_local_aliases(tmp_path):
    """``life = self._life`` then ``with life:`` acquires the same
    node as ``with self._life:`` — aliased re-acquire flags."""
    src = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._life = threading.Lock()\n"
        "    def bad(self):\n"
        "        life = self._life\n"
        "        with self._life:\n"
        "            with life:\n"
        "                pass\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/serving/fake.py": src})
    found = locks.run_locks(ctx)
    assert any("re-acquires" in d.message for d in found), \
        [d.message for d in found]


# ---------------------------------------------------------------------------
# 2. stats-discipline
# ---------------------------------------------------------------------------

_STATS_BAD = (
    "from .profiling import SnapshotStats\n"
    "class S(SnapshotStats):\n"
    "    def __init__(self):\n"
    "        super().__init__()\n"
    "        self.n = 0\n"
    "    def note(self):\n"
    "        self.n += 1\n")
_STATS_GOOD = _STATS_BAD.replace(
    "    def note(self):\n"
    "        self.n += 1\n",
    "    def note(self):\n"
    "        with self._mutating():\n"
    "            self.n += 1\n"
    "    def note2(self):\n"
    "        self._bump(n=1)\n")


def test_stats_discipline_catches_unguarded_mutation(tmp_path):
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/serving/fake.py":
                          _STATS_BAD})
    found = locks.run_stats(ctx)
    assert _codes(found) == ["TM-AUDIT-308"]
    assert "S.note mutates self.n" in found[0].message


def test_stats_discipline_silent_on_guarded_mutation(tmp_path):
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/serving/fake.py":
                          _STATS_GOOD})
    assert locks.run_stats(ctx) == []


# ---------------------------------------------------------------------------
# 2. concurrency (TM-AUDIT-320..323)
# ---------------------------------------------------------------------------

_CONC_FAKE = "transmogrifai_tpu/serving/fake.py"

#: two roots (main via start/read, cb:_loop via the Thread target),
#: field touched by both, no lock anywhere -> 320
_CONC_320_BAD = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._n = 0\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._loop, daemon=True).start()\n"
    "    def _loop(self):\n"
    "        self._n += 1\n"
    "    def read(self):\n"
    "        return self._n\n")

#: repaired: one lock held at every access -> silent
_CONC_GUARDED = (
    "import threading\n"
    "class C:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._n = 0\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._loop, daemon=True).start()\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self._n += 1\n"
    "    def read(self):\n"
    "        with self._lock:\n"
    "            return self._n\n")

#: writes guarded, one read skips the guard -> 321 at the read
_CONC_321_SKIP = _CONC_GUARDED.replace(
    "    def read(self):\n"
    "        with self._lock:\n"
    "            return self._n\n",
    "    def read(self):\n"
    "        return self._n\n")

#: writes under two DIFFERENT locks -> 321 disjoint-guard-sets form
_CONC_321_DISJOINT = (
    "import threading\n"
    "class D:\n"
    "    def __init__(self):\n"
    "        self._a_lock = threading.Lock()\n"
    "        self._b_lock = threading.Lock()\n"
    "        self._n = 0\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._loop, daemon=True).start()\n"
    "    def _loop(self):\n"
    "        with self._a_lock:\n"
    "            self._n += 1\n"
    "    def bump(self):\n"
    "        with self._b_lock:\n"
    "            self._n += 1\n")

#: read under one hold, write under a SEPARATE hold of the same lock,
#: no re-read inside the writing hold -> 322 check-then-act
_CONC_322_BAD = (
    "import threading\n"
    "class E:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._n = 0\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._loop, daemon=True).start()\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self._n += 1\n"
    "    def bump_if_low(self):\n"
    "        with self._lock:\n"
    "            cur = self._n\n"
    "        if cur < 10:\n"
    "            with self._lock:\n"
    "                self._n = cur + 1\n")

#: repaired: check and act merged into ONE hold -> silent
_CONC_322_GOOD = _CONC_322_BAD.replace(
    "    def bump_if_low(self):\n"
    "        with self._lock:\n"
    "            cur = self._n\n"
    "        if cur < 10:\n"
    "            with self._lock:\n"
    "                self._n = cur + 1\n",
    "    def bump_if_low(self):\n"
    "        with self._lock:\n"
    "            cur = self._n\n"
    "            if cur < 10:\n"
    "                self._n = cur + 1\n")

#: guarded mutable container returned LIVE (even under the hold —
#: the caller iterates after release) -> 323
_CONC_323_BAD = (
    "import threading\n"
    "class F:\n"
    "    def __init__(self):\n"
    "        self._lock = threading.Lock()\n"
    "        self._items = []\n"
    "    def start(self):\n"
    "        threading.Thread(target=self._loop, daemon=True).start()\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self._items.append(1)\n"
    "    def snapshot(self):\n"
    "        with self._lock:\n"
    "            return self._items\n")

#: repaired: a copy made inside the hold -> silent
_CONC_323_GOOD = _CONC_323_BAD.replace(
    "            return self._items\n",
    "            return list(self._items)\n")


def test_concurrency_catches_unguarded_shared_field(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_320_BAD})
    found = concurrency.run(ctx)
    assert _codes(found) == ["TM-AUDIT-320"]
    assert "self._n" in found[0].message
    assert "cb:_loop" in found[0].message     # names the thread roots


def test_concurrency_silent_on_consistently_guarded_field(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_GUARDED})
    assert concurrency.run(ctx) == []


def test_concurrency_catches_guard_skipping_read(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_321_SKIP})
    found = concurrency.run(ctx)
    assert _codes(found) == ["TM-AUDIT-321"]
    assert "read without self._lock held" in found[0].message
    # anchored at the bare read, not at the (correct) writes
    assert found[0].location.endswith(":12")


def test_concurrency_catches_disjoint_guard_sets(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_321_DISJOINT})
    found = concurrency.run(ctx)
    assert _codes(found) == ["TM-AUDIT-321"]
    assert "disjoint guard sets" in found[0].message
    assert "self._a_lock" in found[0].message
    assert "self._b_lock" in found[0].message


def test_concurrency_catches_check_then_act(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_322_BAD})
    found = concurrency.run(ctx)
    assert _codes(found) == ["TM-AUDIT-322"]
    assert "check-then-act" in found[0].message


def test_concurrency_silent_on_merged_hold(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_322_GOOD})
    assert concurrency.run(ctx) == []


def test_concurrency_catches_live_container_publication(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_323_BAD})
    found = concurrency.run(ctx)
    assert _codes(found) == ["TM-AUDIT-323"]
    assert "live mutable container self._items" in found[0].message


def test_concurrency_silent_on_copied_publication(tmp_path):
    ctx = _ctx(tmp_path, {_CONC_FAKE: _CONC_323_GOOD})
    assert concurrency.run(ctx) == []


def test_concurrency_condition_canonicalizes_to_wrapped_lock(tmp_path):
    """``Condition(self._lock)`` IS self._lock for guard inference: a
    writer holding the condition and a reader holding the lock agree."""
    src = (
        "import threading\n"
        "class G:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._cond = threading.Condition(self._lock)\n"
        "        self._n = 0\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._loop, "
        "daemon=True).start()\n"
        "    def _loop(self):\n"
        "        with self._cond:\n"
        "            self._n += 1\n"
        "    def read(self):\n"
        "        with self._lock:\n"
        "            return self._n\n")
    ctx = _ctx(tmp_path, {_CONC_FAKE: src})
    assert concurrency.run(ctx) == []


def test_concurrency_inline_lambda_is_not_a_thread_root(tmp_path):
    """A lambda handed to sort()/min() runs inline under the caller's
    hold — only lambdas passed to callback sinks (submit, Thread, ...)
    become roots. One root total -> no shared fields -> silent."""
    src = (
        "import threading\n"
        "class H:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._w = {}\n"
        "    def pick(self, names):\n"
        "        with self._lock:\n"
        "            return sorted(names, "
        "key=lambda n: self._w[n])[0]\n"
        "    def put(self, n, v):\n"
        "        with self._lock:\n"
        "            self._w[n] = v\n")
    ctx = _ctx(tmp_path, {_CONC_FAKE: src})
    assert concurrency.run(ctx) == []


def test_concurrency_suppression_with_reason_waives(tmp_path):
    src = _CONC_320_BAD.replace(
        "        self._n += 1\n",
        "        # opaudit: disable=concurrency -- fixture: "
        "deliberate lock-free counter\n"
        "        self._n += 1\n")
    ctx = _ctx(tmp_path, {_CONC_FAKE: src})
    active, suppressed = core.split_suppressed(
        ctx, concurrency.run(ctx))
    assert active == []
    assert _codes(suppressed) == ["TM-AUDIT-320"]


def test_concurrency_reasonless_suppression_rejected(tmp_path):
    src = _CONC_320_BAD.replace(
        "        self._n += 1\n",
        "        self._n += 1"
        "  # opaudit: disable=concurrency\n")
    ctx = _ctx(tmp_path, {_CONC_FAKE: src})
    assert _codes(core.suppression_findings(ctx)) == ["TM-AUDIT-310"]
    active, suppressed = core.split_suppressed(
        ctx, concurrency.run(ctx))
    assert _codes(active) == ["TM-AUDIT-320"]     # waiver void
    assert suppressed == []


def test_concurrency_real_tree_audits_clean():
    """THE pin for every PR 19 race fix: reverting the tcp.py
    generation gate, the router stop pool capture, the fleet topology
    counts, or the controller status/cooldown holds re-fires a
    TM-AUDIT-32x at that exact line and fails here. Deliberate
    lock-free designs survive only via reasoned suppressions."""
    ctx = core.load_context(_REPO)
    active, suppressed = core.split_suppressed(ctx, concurrency.run(ctx))
    assert active == [], "\n".join(
        f"{d.location}: {d.message}" for d in active)
    # the suppression inventory is intentional, not incidental: the
    # Event-sequenced worker flag, the engine admission fast path and
    # the autoscaler single-flight protocol all carry written reasons
    assert len(suppressed) >= 5


# ---------------------------------------------------------------------------
# 2. clone detection
# ---------------------------------------------------------------------------

def _driver(name, tweak="0.01"):
    return (
        f"def {name}(fleet, rps, seconds, rng):\n"
        "    sent, results, errors, lost = [], [], [], []\n"
        "    t0 = time.monotonic()\n"
        "    deadline = t0 + seconds\n"
        "    while time.monotonic() < deadline:\n"
        "        gap = rng.exponential(1.0 / rps)\n"
        f"        time.sleep(min(gap, {tweak}))\n"
        "        n = int(rng.integers(1, 30))\n"
        "        try:\n"
        "            fut = fleet.submit(n, timeout=5.0)\n"
        "        except RuntimeError as e:\n"
        "            errors.append(e)\n"
        "            continue\n"
        "        sent.append((n, fut))\n"
        "    for n, fut in sent:\n"
        "        try:\n"
        "            results.append((n, fut.result(timeout=30.0)))\n"
        "        except TimeoutError:\n"
        "            lost.append(n)\n"
        "        except RuntimeError as e:\n"
        "            errors.append(e)\n"
        "    waits = sorted(r[1] for r in results)\n"
        "    p50 = waits[len(waits) // 2] if waits else 0.0\n"
        "    p99 = waits[int(len(waits) * 0.99)] if waits else 0.0\n"
        "    return {'sent': len(sent), 'errors': len(errors),\n"
        "            'lost': len(lost), 'p50': p50, 'p99': p99}\n")


def test_clone_catches_pasted_poisson_driver(tmp_path):
    src = "import time\n\n" + _driver("drive_a") + "\n" \
        + _driver("drive_b", tweak="0.02")
    ctx = _ctx(tmp_path, {"tests/fake_bench_test.py": src})
    found = clones.run(ctx)
    assert _codes(found) == ["TM-AUDIT-309"]
    assert "drive_b" in found[0].message
    assert "drive_a" in found[0].message


def test_clone_silent_on_genuinely_different_functions(tmp_path):
    other = (
        "def build_report(rows):\n"
        + "".join(f"    k{i} = sum(r[{i}] for r in rows)\n"
                  for i in range(30))
        + "    return [" + ", ".join(f"k{i}" for i in range(30))
        + "]\n")
    src = "import time\n\n" + _driver("drive_a") + "\n" + other
    ctx = _ctx(tmp_path, {"tests/fake_bench_test.py": src})
    assert clones.run(ctx) == []


# ---------------------------------------------------------------------------
# 2. suppression hygiene + the waiver machinery itself
# ---------------------------------------------------------------------------

def test_suppression_with_reason_suppresses_and_is_reported(tmp_path):
    src = ("import os\n"
           "X = os.environ.get('TM_FAKE_RAW_KNOB')"
           "  # opaudit: disable=knob-registry -- fixture waiver\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": src})
    active, suppressed = core.split_suppressed(
        ctx, knobs.run_registry(ctx))
    assert active == []
    assert _codes(suppressed) == ["TM-AUDIT-302"]


def test_comment_above_form_suppresses(tmp_path):
    src = ("import os\n"
           "# opaudit: disable=knob-registry -- fixture waiver\n"
           "X = os.environ.get('TM_FAKE_RAW_KNOB')\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": src})
    active, suppressed = core.split_suppressed(
        ctx, knobs.run_registry(ctx))
    assert active == [] and len(suppressed) == 1


def test_reasonless_suppression_rejected_and_does_not_waive(tmp_path):
    src = ("import os\n"
           "X = os.environ.get('TM_FAKE_RAW_KNOB')"
           "  # opaudit: disable=knob-registry\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": src})
    hygiene = core.suppression_findings(ctx)
    assert _codes(hygiene) == ["TM-AUDIT-310"]
    active, suppressed = core.split_suppressed(
        ctx, knobs.run_registry(ctx))
    assert _codes(active) == ["TM-AUDIT-302"]     # waiver void
    assert suppressed == []


def test_unknown_pass_suppression_rejected(tmp_path):
    src = "# opaudit: disable=no-such-pass -- because\nX = 1\n"
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": src})
    (d,) = core.suppression_findings(ctx)
    assert d.code == "TM-AUDIT-310"
    assert "no-such-pass" in d.message


def test_suppression_findings_not_self_suppressible(tmp_path):
    src = ("# opaudit: disable=knob-registry\n")
    ctx = _ctx(tmp_path, {"transmogrifai_tpu/fake.py": src})
    active, suppressed = core.split_suppressed(
        ctx, core.suppression_findings(ctx))
    assert _codes(active) == ["TM-AUDIT-310"]


# ---------------------------------------------------------------------------
# changed-only mode
# ---------------------------------------------------------------------------

def test_changed_only_filters_to_listed_files(tmp_path):
    files = {
        "transmogrifai_tpu/one.py":
            "import os\nA = os.environ.get('TM_FAKE_ONE')\n",
        "transmogrifai_tpu/two.py":
            "import os\nB = os.environ.get('TM_FAKE_TWO')\n",
    }
    ctx = _ctx(tmp_path, files)
    full = core.run_audit(str(tmp_path), passes=["knob-registry"],
                          ctx=ctx)
    assert len(full["findings"]) == 2
    ctx2 = _ctx(tmp_path, files)
    part = core.run_audit(str(tmp_path), passes=["knob-registry"],
                          changed_only=["transmogrifai_tpu/two.py"],
                          ctx=ctx2)
    assert [f["location"] for f in part["findings"]] \
        == ["transmogrifai_tpu/two.py:2"]
