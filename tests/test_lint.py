"""opcheck static analyzer tests.

Two halves:

* the **known-bad zoo** — one minimal workflow (or source snippet) per
  diagnostic code, asserting the exact stable code fires; and
* **zero-findings** runs — every example workflow, representative
  testkit-built workflows, and the generated `gen` project template
  must lint completely clean (the no-false-positives contract that
  makes the linter usable as a CI gate).

The AST-layer zoo cases run on SOURCE TEXT via analyze_source — the
stage under test is never imported or executed.
"""
import json
import os
import sys

import numpy as np
import pytest

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu import models as M
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.features.feature import Feature
from transmogrifai_tpu.features.manifest import NULL_INDICATOR
from transmogrifai_tpu.lint import (LintError, analyze_source,
                                    analyze_stage_class,
                                    check_export_manifest, lint_artifact,
                                    lint_model, lint_workflow)
from transmogrifai_tpu.ops.parsers import DropIndicesByTransformer
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.transmogrifier import (transmogrify,
                                                  transmogrify_sparse)
from transmogrifai_tpu.ops.vectorizers import (RealVectorizer,
                                               VectorsCombiner)
from transmogrifai_tpu.stages.base import (LambdaTransformer,
                                           UnaryTransformer)
from transmogrifai_tpu.workflow import Workflow

EXAMPLES_DIR = os.path.join(os.path.dirname(__file__), "..", "examples")


def _resp(name="y"):
    return FeatureBuilder.of(ft.RealNN, name).from_column().as_response()


def _real(name):
    return FeatureBuilder.of(ft.Real, name).from_column().as_predictor()


def _binary_workflow():
    y, x1, x2 = _resp(), _real("x1"), _real("x2")
    fv = transmogrify([x1, x2])
    checked = SanityChecker().set_input(y, fv).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, candidates=[["LogisticRegression", {"regParam": [0.01]}]]
    ).set_input(y, checked).output
    return Workflow([pred]), y, pred


# ---------------------------------------------------------------------------
# Known-bad zoo: graph layer
# ---------------------------------------------------------------------------

def test_zoo_type_mismatch_001():
    # LambdaTransformer skips runtime input checks — the linter does not
    x = _real("x")
    t = LambdaTransformer(lambda v: v, ft.Real, operation_name="id")
    t.in_types = (ft.Text,)               # declared Text, wired Real
    bad = t.set_input(x).output
    codes = lint_workflow([bad]).codes()
    assert "TM-LINT-001" in codes


def test_zoo_arity_mismatch_001():
    x = _real("x")
    t = LambdaTransformer(lambda a, b: a, ft.Real, operation_name="two")
    t.in_types = (ft.Real, ft.Real)       # declared 2 inputs, wired 1
    bad = t.set_input(x).output
    assert "TM-LINT-001" in lint_workflow([bad]).codes()


def test_zoo_cycle_002():
    f1 = Feature("a", ft.Real, parents=())
    st = LambdaTransformer(lambda v: v, ft.Real, operation_name="loop")
    f2 = st.set_input(f1).output
    # forge the back edge (Feature is immutable through normal channels)
    object.__setattr__(f1, "parents", (f2,))
    report = lint_workflow([f2])
    assert "TM-LINT-002" in report.codes()


def test_zoo_duplicate_stage_uid_003():
    b1, b2 = _real("b1"), _real("b2")
    s1 = RealVectorizer()
    s2 = RealVectorizer(uid=s1.uid)       # forged duplicate uid
    v1 = s1.set_input(b1).output
    v2 = s2.set_input(b2).output
    merged = VectorsCombiner().set_input(v1, v2).output
    assert "TM-LINT-003" in lint_workflow([merged]).codes()


def test_zoo_duplicate_output_name_004():
    a1 = _real("dup_col")
    a2 = FeatureBuilder.of(ft.Real, "dup_col").from_column().as_predictor()
    v1 = RealVectorizer().set_input(a1).output
    v2 = RealVectorizer().set_input(a2).output
    merged = VectorsCombiner().set_input(v1, v2).output
    assert "TM-LINT-004" in lint_workflow([merged]).codes()


def test_zoo_response_leakage_005():
    y = _resp()
    leak = RealVectorizer().set_input(y).output     # vectorized the label
    fv = VectorsCombiner().set_input(leak).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2).set_input(y, fv).output
    report = lint_workflow([pred])
    assert "TM-LINT-005" in report.codes()
    leak_findings = [d for d in report if d.code == "TM-LINT-005"]
    assert any("y" in (d.feature or "") for d in leak_findings)


def test_zoo_stacked_leakage_005_via_post_model_taint():
    """A post-model stage may reference the response legitimately
    (descaling) — but when its output re-enters a second model's
    feature path, the carried response data is a leak again."""
    y = _resp()
    x = _real("x")
    fv = transmogrify([x])
    pred1 = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2).set_input(y, fv).output
    # post-model stage consuming (Prediction, response): exempt locally
    post = LambdaTransformer(lambda p, r: p, ft.Real,
                             operation_name="descaleLike")
    carried = post.set_input(pred1, y).output
    carried_vec = RealVectorizer().set_input(carried).output
    fv2 = VectorsCombiner().set_input(carried_vec).output
    pred2 = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2).set_input(y, fv2).output
    report = lint_workflow([pred2], ast_checks=False)
    assert "TM-LINT-005" in report.codes()
    # the SAME post-model stage with no second model downstream is clean
    assert lint_workflow([carried], ast_checks=False).codes() == []


def test_zoo_dead_feature_006():
    wf, y, pred = _binary_workflow()
    orphan = RealVectorizer().set_input(_real("orphan")).output
    report = lint_workflow(wf, extra_features=[orphan])
    assert "TM-LINT-006" in report.codes()
    # the same workflow with no orphan declared is clean
    assert lint_workflow(wf).codes() == []


def test_zoo_export_skew_007():
    manifest = {
        "boundary": ["a", "b"],
        "responseBoundary": ["nope"],                 # not in boundary
        "resultNames": ["ghost"],                     # never produced
        "stages": [{"out": "c", "inputs": ["a", "missing"]}],
    }
    codes = [d.code for d in check_export_manifest(manifest)]
    assert codes.count("TM-LINT-007") >= 3
    # cross-check against live terminal outputs
    ok = {"boundary": ["a"], "responseBoundary": [],
          "resultNames": ["c"], "stages": [{"out": "c", "inputs": ["a"]}]}
    assert check_export_manifest(ok) == []
    skew = [d.code for d in check_export_manifest(
        ok, result_names=["other_terminal"])]
    assert "TM-LINT-007" in skew


def test_zoo_bucket_skew_008():
    base = {"boundary": ["a"], "responseBoundary": [], "resultNames": [],
            "stages": []}
    bad = dict(base, scoreBuckets=[0, 64])            # non-positive
    assert "TM-LINT-008" in [d.code for d in check_export_manifest(bad)]
    unsorted = dict(base, scoreBuckets=[128, 64])     # not normalized
    assert "TM-LINT-008" in [d.code
                             for d in check_export_manifest(unsorted)]
    good = dict(base, scoreBuckets=[64, 128])
    assert check_export_manifest(good) == []


class _UnstableSigTransformer(UnaryTransformer):
    in_type = ft.Real
    out_type = ft.Real
    operation_name = "unstableSig"
    device_fn_exact = True

    def transform_value(self, v):
        return v

    def make_device_fn(self):
        return lambda x: x

    def device_fn_signature(self):
        # the classic retrace bug: identity leaks into the cache key,
        # so identical configs never hit the same compiled program
        import itertools
        if not hasattr(type(self), "_sig_counter"):
            type(self)._sig_counter = itertools.count()
        return ("unstable", next(type(self)._sig_counter))


def test_zoo_retrace_hazard_009():
    x = _real("x")
    bad = _UnstableSigTransformer().set_input(x).output
    report = lint_workflow([bad], ast_checks=False)
    assert "TM-LINT-009" in report.codes()


def test_zoo_degrade_feeds_model_010():
    """A failure_policy='degrade' stage whose output feeds the model's
    feature-vector slot NON-optionally: degrading it would silently
    change what the model trains on."""
    y, x1, x2 = _resp(), _real("x1"), _real("x2")
    combined = VectorsCombiner().with_failure_policy("degrade") \
        .set_input(RealVectorizer().set_input(x1).output,
                   RealVectorizer().set_input(x2).output).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, candidates=[["LogisticRegression",
                                {"regParam": [0.01]}]]
    ).set_input(y, combined).output
    report = lint_workflow([pred], ast_checks=False)
    assert "TM-LINT-010" in report.codes()
    assert report.has_errors


def test_zoo_degrade_label_slot_010():
    """A degrade-marked stage producing the supervision input."""
    y, x1 = _resp(), _real("x1")
    scaled = LambdaTransformer(abs, ft.RealNN, operation_name="scaleY")
    scaled.failure_policy = "degrade"

    def resp_out(features):
        return True
    scaled.output_is_response = resp_out
    y2 = scaled.set_input(y).output
    fv = transmogrify([x1])
    checked = SanityChecker().set_input(y2, fv).output
    report = lint_workflow([checked], ast_checks=False)
    assert "TM-LINT-010" in report.codes()


def test_degrade_through_variadic_combiner_is_clean():
    """The SAFE degrade wiring: the degradable output rides a variadic
    combiner tail slot, which simply shrinks when the stage degrades —
    no finding."""
    y, x1, x2 = _resp(), _real("x1"), _real("x2")
    enrich = RealVectorizer().with_failure_policy("degrade") \
        .set_input(x1).output
    fv = transmogrify([x1, x2])
    combined = VectorsCombiner().set_input(fv, enrich).output
    checked = SanityChecker().set_input(y, combined).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, candidates=[["LogisticRegression",
                                {"regParam": [0.01]}]]
    ).set_input(y, checked).output
    report = lint_workflow([pred], ast_checks=False)
    assert "TM-LINT-010" not in report.codes()


# ---------------------------------------------------------------------------
# Known-bad zoo: AST layer (source text only — never imported/executed)
# ---------------------------------------------------------------------------

_MUTATING_ROW_SRC = '''
class CountingTransformer:
    def transform_value(self, v):
        self.calls = getattr(self, "calls", 0) + 1
        return v
'''

_UNMARKED_CACHE_SRC = '''
class CachingCombiner:
    def _transform_columns(self, ds):
        out = build(ds)
        self.manifest = out.manifest      # cached, but no marker
        return out
'''

_MARKED_CACHE_SRC = '''
class DeclaredCachingCombiner:
    transform_caches_state = True
    def _transform_columns(self, ds):
        out = build(ds)
        self.manifest = out.manifest
        return out
'''

_NONDET_SRC = '''
import numpy as np
class JitteryTransformer:
    def transform_value(self, v):
        return v + np.random.random()
'''

_GLOBAL_SRC = '''
_CALLS = 0
class GlobalCounter:
    def transform(self, ds):
        global _CALLS
        _CALLS += 1
        return ds
'''


def test_zoo_self_mutation_201_from_source_only():
    codes = [d.code for d in analyze_source(_MUTATING_ROW_SRC)]
    assert codes == ["TM-LINT-201"]


def test_zoo_missing_cache_marker_202_from_source_only():
    codes = [d.code for d in analyze_source(_UNMARKED_CACHE_SRC)]
    assert codes == ["TM-LINT-202"]
    # declaring the marker clears the finding (VectorsCombiner pattern)
    assert analyze_source(_MARKED_CACHE_SRC) == []


def test_zoo_nondeterminism_203():
    codes = [d.code for d in analyze_source(_NONDET_SRC)]
    assert "TM-LINT-203" in codes


def test_zoo_global_state_204():
    codes = [d.code for d in analyze_source(_GLOBAL_SRC)]
    assert "TM-LINT-204" in codes


class _LiveMutatingTransformer(UnaryTransformer):
    in_type = ft.Real
    out_type = ft.Real
    operation_name = "liveMut"

    def transform_value(self, v):
        self.last_value = v               # the race the lint exists for
        return v


def test_live_class_analysis_and_workflow_integration():
    assert ["TM-LINT-201"] == [
        d.code for d in analyze_stage_class(_LiveMutatingTransformer)]
    x = _real("x")
    bad = _LiveMutatingTransformer().set_input(x).output
    assert "TM-LINT-201" in lint_workflow([bad]).codes()


def test_builtin_stages_are_clean():
    # the declared cachers (VectorsCombiner, DropIndicesByTransformer)
    # carry the marker, so the AST pass reports nothing
    assert analyze_stage_class(VectorsCombiner) == []
    assert analyze_stage_class(DropIndicesByTransformer) == []
    assert DropIndicesByTransformer.transform_caches_state is True


# ---------------------------------------------------------------------------
# Construction-time hard errors (the compute_dag integrity gate)
# ---------------------------------------------------------------------------

def test_workflow_construction_rejects_duplicate_output_name():
    a1 = _real("same")
    a2 = FeatureBuilder.of(ft.Real, "same").from_column().as_predictor()
    v1 = RealVectorizer().set_input(a1).output
    v2 = RealVectorizer().set_input(a2).output
    merged = VectorsCombiner().set_input(v1, v2).output
    with pytest.raises(ValueError, match="duplicate output feature name"):
        Workflow([merged])


def test_workflow_construction_rejects_duplicate_stage_uid():
    b1, b2 = _real("u1"), _real("u2")
    s1 = RealVectorizer()
    s2 = RealVectorizer(uid=s1.uid)
    v1 = s1.set_input(b1).output
    v2 = s2.set_input(b2).output
    merged = VectorsCombiner().set_input(v1, v2).output
    with pytest.raises(ValueError, match="duplicate stage uid|stage uid"):
        Workflow([merged])


# ---------------------------------------------------------------------------
# Train gate (TM_LINT / lint=) and findings surfacing
# ---------------------------------------------------------------------------

def _leaky_workflow_features():
    y = _resp()
    leak = RealVectorizer().set_input(y).output
    fv = VectorsCombiner().set_input(leak).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2).set_input(y, fv).output
    return [pred]


def test_train_gate_strict_raises_before_fitting():
    wf = Workflow(_leaky_workflow_features())
    with pytest.raises(LintError, match="TM-LINT-005"):
        wf.train([{"y": 1.0}], lint="strict")   # no usable data needed:
    # the gate fires before anything is read or fitted


def test_train_gate_warn_records_findings(rng, capsys):
    rows = [{"y": float(i % 2), "x1": float(i), "x2": float(i * 3 % 7)}
            for i in range(40)]
    wf, y, pred = _binary_workflow()
    model = wf.train(rows, lint="warn")
    lf = model.train_summaries["lintFindings"]
    assert lf == {"findings": [], "errors": 0, "warnings": 0}
    # surfaced through model_insights
    assert model.model_insights()["lintFindings"] == lf


def test_train_gate_off_by_default(rng):
    rows = [{"y": float(i % 2), "x1": float(i), "x2": float(i * 3 % 7)}
            for i in range(40)]
    wf, y, pred = _binary_workflow()
    model = wf.train(rows)
    assert "lintFindings" not in model.train_summaries
    # a gate-off RETRAIN must not inherit a previous gated train's report
    wf.train(rows, lint="warn")
    model3 = wf.train(rows)
    assert "lintFindings" not in model3.train_summaries


def test_resolve_lint_mode_spellings():
    from transmogrifai_tpu.lint import resolve_lint_mode
    assert resolve_lint_mode("on") == "warn"
    assert resolve_lint_mode("1") == "warn"
    assert resolve_lint_mode("true") == "warn"
    assert resolve_lint_mode("false") == "off"
    assert resolve_lint_mode("0") == "off"
    assert resolve_lint_mode("strict") == "strict"
    with pytest.raises(ValueError, match="unknown TM_LINT mode"):
        resolve_lint_mode("stric")


# ---------------------------------------------------------------------------
# transform_caches_state audit regression: DropIndicesByTransformer
# ---------------------------------------------------------------------------

def test_drop_indices_state_survives_parallel_executor(tmp_path):
    """The parallel executor lifetime-skips transforms with no
    downstream consumer; DropIndicesByTransformer resolves its
    match_fn indices INSIDE transform, so an unmarked skip would leave
    them unresolved and persistence would fail (TM-LINT-202)."""
    rows = [{"y": float(i % 2), "x1": float(i) if i % 3 else None,
             "x2": float(i * 2)} for i in range(30)]
    y, x1, x2 = _resp(), _real("x1"), _real("x2")
    fv = transmogrify([x1, x2])
    # terminal stage: output has NO downstream consumer -> skip-eligible
    dropped = DropIndicesByTransformer(
        match_fn=lambda c: c.indicator_value == NULL_INDICATOR
    ).set_input(fv).output
    model = Workflow([dropped]).train(rows, executor="parallel")
    drop_stage = model.stage_by_output(dropped.name)
    assert drop_stage.params["drop_indices"], \
        "match_fn indices must resolve during train (transform ran)"
    model.save(str(tmp_path / "m"))       # would raise if unresolved


# ---------------------------------------------------------------------------
# Zero findings: examples, testkit builders, gen template, artifacts
# ---------------------------------------------------------------------------

def _import_example(name):
    sys.path.insert(0, EXAMPLES_DIR)
    try:
        import importlib
        return importlib.import_module(name)
    finally:
        sys.path.remove(EXAMPLES_DIR)


@pytest.mark.parametrize("name", ["op_iris", "op_titanic_simple",
                                  "op_boston", "op_house_log",
                                  "op_ctr_sparse"])
def test_examples_lint_clean(name):
    mod = _import_example(name)
    report = lint_workflow(mod.build_workflow())
    assert report.codes() == [], report.format_text()


def test_testkit_builder_workflows_lint_clean():
    from transmogrifai_tpu.testkit import TestFeatureBuilder
    ds, feats = TestFeatureBuilder.of({
        "label": (ft.RealNN, [0.0, 1.0, 1.0, 0.0]),
        "age": (ft.Real, [1.0, 2.0, None, 4.0]),
        "city": (ft.PickList, ["sf", "la", "sf", None]),
        "tags": (ft.MultiPickList, [["a"], ["b"], [], ["a", "b"]]),
        "scores": (ft.RealMap, [{"m": 1.0}, {}, {"m": 2.0}, {"n": 3.0}]),
        "geo": (ft.Geolocation, [(37.0, -122.0, 1.0), (), (), ()]),
        "when": (ft.Date, [1, 2, 3, None]),
    }, response="label")
    fv = transmogrify([feats[n] for n in
                       ("age", "city", "tags", "scores", "geo", "when")])
    checked = SanityChecker().set_input(feats["label"], fv).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2).set_input(feats["label"], checked).output
    report = lint_workflow(Workflow([pred]))
    assert report.codes() == [], report.format_text()

    # sparse (Criteo-style) builder workflow
    ds2, f2 = TestFeatureBuilder.of({
        "click": (ft.RealNN, [0.0, 1.0]),
        "cat": (ft.PickList, ["a", "b"]),
        "num": (ft.Real, [1.0, 2.0]),
    }, response="click")
    hashed, dense = transmogrify_sparse([f2["cat"], f2["num"]],
                                        num_buckets=1 << 10)
    from transmogrifai_tpu.models.sparse import SparseModelSelector
    spred = SparseModelSelector(num_buckets=1 << 10, n_folds=2).set_input(
        f2["click"], hashed, dense).output
    report2 = lint_workflow(Workflow([spred]))
    assert report2.codes() == [], report2.format_text()


def test_gen_template_lints_clean_via_cli(tmp_path):
    """CI contract: the generated project template must pass
    `python -m transmogrifai_tpu lint --project ...` with exit code 0."""
    from transmogrifai_tpu import cli
    csv = tmp_path / "data.csv"
    rows = ["label,f1,f2,cat"]
    rows += [f"{i % 2},{i},{i * 2},{'ab'[i % 2]}" for i in range(30)]
    csv.write_text("\n".join(rows) + "\n")
    proj = tmp_path / "proj"
    cli.generate_project(str(csv), "label", str(proj))
    rc = cli.main(["lint", "--project", str(proj)])
    assert rc == 0
    # json format carries the structured report
    rc = cli.main(["lint", "--project", str(proj), "--format", "json"])
    assert rc == 0


def test_cli_lint_exits_nonzero_on_errors(tmp_path, capsys):
    from transmogrifai_tpu import cli
    # a portable manifest with skew: the CLI must gate (exit 1)
    bad_dir = tmp_path / "bad_artifact"
    bad_dir.mkdir()
    (bad_dir / "manifest.json").write_text(json.dumps({
        "boundary": ["a"], "responseBoundary": ["ghost"],
        "resultNames": ["never_produced"], "stages": [],
        "scoreBuckets": [0],
    }))
    rc = cli.main(["lint", "--model", str(bad_dir), "--format", "json"])
    assert rc == 1
    out = json.loads(capsys.readouterr().out)
    codes = {f["code"] for f in out["findings"]}
    assert {"TM-LINT-007", "TM-LINT-008"} <= codes


# ---------------------------------------------------------------------------
# Artifact / registry publish gate
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def trained_model(tmp_path_factory):
    rows = [{"y": float(i % 2), "x1": float(i), "x2": float(i * 3 % 11)}
            for i in range(60)]
    y, x1, x2 = _resp(), _real("x1"), _real("x2")
    fv = transmogrify([x1, x2])
    checked = SanityChecker().set_input(y, fv).output
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, candidates=[["LogisticRegression", {"regParam": [0.01]}]]
    ).set_input(y, checked).output
    return Workflow([pred]).train(rows)


def test_fitted_model_and_export_lint_clean(trained_model, tmp_path):
    assert lint_model(trained_model).codes() == []
    out = tmp_path / "artifact"
    trained_model.export_portable(str(out), buckets=(64, 256))
    report = lint_artifact(str(out))
    assert report.codes() == [], report.format_text()


def test_statusz_surfaces_waived_findings(tmp_path):
    """TM_LINT=warn findings ride train_summaries into the serving
    engine's /statusz snapshot for the version serving traffic."""
    from transmogrifai_tpu.serving import ServingEngine
    from transmogrifai_tpu.serving.health import status_snapshot
    rows = [{"y": float(i % 2), "x1": float(i), "x2": float(i * 3 % 11)}
            for i in range(40)]
    wf, y, pred = _binary_workflow()
    model = wf.train(rows, lint="warn")
    assert "lintFindings" in model.train_summaries
    with ServingEngine(model, buckets=(32,)) as eng:
        snap = status_snapshot(eng)
        (version_stats,) = snap["scoring"].values()
        assert version_stats["lintFindings"] == \
            model.train_summaries["lintFindings"]


def test_registry_rejects_skewed_artifact_before_publish(trained_model,
                                                         tmp_path):
    from transmogrifai_tpu.serving import ModelRegistry
    out = tmp_path / "artifact"
    trained_model.export_portable(str(out), buckets=(64, 256))
    man_path = out / "manifest.json"
    doc = json.loads(man_path.read_text())
    doc["resultNames"] = ["someone_elses_prediction"]
    man_path.write_text(json.dumps(doc))
    # the pre-publish gate refuses the version; nothing can hot-swap it
    with pytest.raises(LintError, match="TM-LINT-007"):
        ModelRegistry().register("v_bad", str(out), warm=False)
    # the standalone artifact lint reports the same skew
    assert "TM-LINT-007" in lint_artifact(str(out)).codes()
