"""Serving-engine subsystem tests.

Pins the tentpole guarantees: concurrent mixed-size requests coalesce
into micro-batches yet score BITWISE-equal to solo scoring, the compile
universe stays bounded by the bucket set (warm included), hot-swap loses
zero accepted requests, admission control sheds/rejects loudly (every
degraded decision lands in a counter and an exception), and the merged
health snapshot carries torn-read-detectable snapshot_seq counters.
"""
import json
import os
import threading
import time

import numpy as np
import pytest

from serving_util import train_small_serving_model

from transmogrifai_tpu import Dataset


def _train(seed: int):
    return train_small_serving_model(seed)


@pytest.fixture(scope="module")
def served():
    return _train(3)


@pytest.fixture(scope="module")
def served_v2():
    return _train(17)


def _slice(ds, n0, n1):
    return Dataset({k: ds.column(k)[n0:n1] for k in ds.column_names},
                   {k: ds.ftype(k) for k in ds.column_names})


# ---------------------------------------------------------------------------
# tentpole: coalescing correctness + compile bound under concurrency
# ---------------------------------------------------------------------------

def test_concurrent_mixed_sizes_bitwise_equal_and_compile_bound(served):
    """16 client threads, mixed batch sizes: every caller gets exactly
    its own rows, bitwise-equal to solo scoring; total compiles (warm
    included) stay <= len(buckets); requests really coalesced."""
    from transmogrifai_tpu.serving import EngineConfig, ServingEngine

    model, ds, _ = served
    naive = model.compile_scoring()
    buckets = (32, 64, 128)
    rng = np.random.default_rng(5)
    sizes = [int(s) for s in rng.integers(1, 150, size=16)]
    refs = [naive.score_arrays(_slice(ds, 0, s)) for s in sizes]

    with ServingEngine(model, buckets=buckets,
                       warm_sample=_slice(ds, 0, 1),
                       config=EngineConfig(max_wait_ms=4.0)) as eng:
        results = [None] * len(sizes)
        errors = []

        def client(i, s):
            try:
                results[i] = eng.score(_slice(ds, 0, s), timeout=60)
            except Exception as e:          # pragma: no cover - fail loud
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i, s))
                   for i, s in enumerate(sizes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for i, (ref, got) in enumerate(zip(refs, results)):
            assert set(ref) == set(got)
            for k in ref:
                assert ref[k].shape == got[k].shape
                assert np.array_equal(ref[k], got[k]), (i, sizes[i], k)

        scoring = eng.registry.get().backend.stats
        assert 0 < scoring.total_compiles <= len(buckets)
        assert set(scoring.compiles) <= set(buckets)
        est = eng.status()
        assert est["engine"]["submitted"] == len(sizes)
        assert est["engine"]["completed"] == len(sizes)
        assert est["engine"]["failed"] == 0
        assert est["engine"]["shed_expired"] == 0
        # coalescing actually happened (strictly fewer batches than
        # requests would be flaky under thread scheduling; bound loosely)
        assert 1 <= est["engine"]["batches"] <= len(sizes)


def test_single_request_path_and_empty_queue_idle(served):
    from transmogrifai_tpu.serving import ServingEngine

    model, ds, pred_name = served
    naive = model.compile_scoring()
    with ServingEngine(model, buckets=(32, 64)) as eng:
        ref = naive.score_arrays(_slice(ds, 0, 9))
        got = eng.score(_slice(ds, 0, 9), timeout=60)
        for k in ref:
            assert np.array_equal(ref[k], got[k])
        assert eng.ready() and eng.live()
    assert not eng.live()       # stop() joined the dispatcher


# ---------------------------------------------------------------------------
# tentpole: hot-swap
# ---------------------------------------------------------------------------

def test_hot_swap_mid_traffic_loses_zero_accepted_requests(served,
                                                           served_v2):
    """Client threads hammer the engine while the main thread hot-swaps
    to a different model. Every accepted request completes and its
    result is bitwise-equal to solo scoring under ONE of the two
    versions (never a blend, never a loss); the old version drains and
    releases."""
    from transmogrifai_tpu.serving import EngineConfig, ServingEngine

    model1, ds, _ = served
    model2, _, _ = served_v2
    ref1 = {n: model1.compile_scoring().score_arrays(_slice(ds, 0, n))
            for n in (3, 11, 20)}
    ref2 = {n: model2.compile_scoring().score_arrays(_slice(ds, 0, n))
            for n in (3, 11, 20)}

    with ServingEngine(model1, buckets=(32, 64),
                       warm_sample=_slice(ds, 0, 1), version="v1",
                       config=EngineConfig(max_wait_ms=1.0)) as eng:
        stop_clients = threading.Event()
        outcomes, errors = [], []
        lock = threading.Lock()

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop_clients.is_set():
                n = int(rng.choice([3, 11, 20]))
                try:
                    got = eng.score(_slice(ds, 0, n), timeout=60)
                except Exception as e:
                    errors.append(e)
                    return
                with lock:
                    outcomes.append((n, got))

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(6)]
        for t in threads:
            t.start()
        # let traffic flow, then swap mid-stream
        while True:
            with lock:
                if len(outcomes) >= 10:
                    break
            time.sleep(0.01)
        prev = eng.swap("v2", model2, warm_sample=_slice(ds, 0, 1))
        assert prev == "v1"
        while True:
            with lock:
                if len(outcomes) >= 30:
                    break
            time.sleep(0.01)
        stop_clients.set()
        for t in threads:
            t.join()

        assert not errors
        # result-feature NAMES embed uid counters and may differ between
        # the two independently-built models — compare positionally (both
        # pipelines expose exactly one prediction result)
        n_v2 = 0
        for n, got in outcomes:
            (gv,) = got.values()
            (r1,) = ref1[n].values()
            (r2,) = ref2[n].values()
            if np.array_equal(r1, gv):
                continue
            n_v2 += 1
            assert np.array_equal(r2, gv)    # one version, never a blend
        st = eng.status()
        assert st["default_version"] == "v2"
        assert st["engine"]["swaps"] == 1
        assert st["engine"]["failed"] == 0
        assert st["versions"]["v1"]["retired"]
        assert st["versions"]["v1"]["released"]
        assert st["versions"]["v1"]["inflight"] == 0
        # post-swap traffic really scored on v2
        post = eng.score(_slice(ds, 0, 11), timeout=60)
        (pv,) = post.values()
        (r2,) = ref2[11].values()
        assert np.array_equal(r2, pv)
        assert n_v2 >= 1


def test_queued_request_reprepares_after_name_reuse(served, served_v2):
    """A request queued before a swap must re-prepare even when the
    serving version REUSES a released name (rollback): staleness is
    backend identity, not version-name equality."""
    from transmogrifai_tpu.serving import EngineConfig, ServingEngine

    model1, ds, _ = served
    model2, _, _ = served_v2
    eng = ServingEngine(model1, buckets=(32,), warm_sample=_slice(ds, 0, 1),
                        version="v1", config=EngineConfig(max_wait_ms=50.0))
    eng._accepting = True            # queue BEFORE the dispatcher runs
    fut = eng.submit(_slice(ds, 0, 9))
    # swap away, then roll back a DIFFERENT model under the old name
    eng.swap("v2", model2, buckets=(32,), warm_sample=_slice(ds, 0, 1))
    eng.swap("v1", model2, buckets=(32,), warm_sample=_slice(ds, 0, 1))
    eng.start()
    (got,) = fut.result(30).values()
    (ref,) = model2.compile_scoring().score_arrays(
        _slice(ds, 0, 9)).values()
    assert np.array_equal(ref, got)   # scored by the CURRENT "v1"
    eng.stop()


def test_swap_warms_before_flip(served, served_v2):
    """The new version's buckets compile during swap() BEFORE it takes
    traffic: its ScoringStats show len(buckets) compiles at flip time,
    and traffic afterwards adds none."""
    from transmogrifai_tpu.serving import ServingEngine

    model1, ds, _ = served
    model2, _, _ = served_v2
    buckets = (32, 64)
    with ServingEngine(model1, buckets=buckets,
                       warm_sample=_slice(ds, 0, 1)) as eng:
        eng.swap("v2", model2, buckets=buckets,
                 warm_sample=_slice(ds, 0, 1))
        v2 = eng.registry.get("v2")
        assert v2.warmed
        assert v2.backend.stats.total_compiles == len(buckets)
        # warm compiles are counted but warm ROWS are not traffic: the
        # serving counters must start clean or /statusz rows_per_sec
        # and padding_overhead report phantom rows
        assert v2.backend.stats.total_rows == 0
        eng.score(_slice(ds, 0, 40), timeout=60)
        assert v2.backend.stats.total_compiles == len(buckets)
        assert v2.backend.stats.total_rows == 40


# ---------------------------------------------------------------------------
# admission control
# ---------------------------------------------------------------------------

def test_queue_full_backpressure(served):
    from transmogrifai_tpu.serving import (EngineConfig, QueueFull,
                                           ServingEngine)

    model, ds, _ = served
    cfg = EngineConfig(max_queue_rows=25, max_queue_requests=2,
                       max_wait_ms=50.0)
    eng = ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1),
                        config=cfg)
    # engine NOT started: the queue only fills
    eng._accepting = True
    eng.submit(_slice(ds, 0, 10))
    eng.submit(_slice(ds, 0, 10))
    with pytest.raises(QueueFull):
        eng.submit(_slice(ds, 0, 10))       # request-count bound
    st = eng.stats.as_dict()
    assert st["rejected_queue_full"] == 1
    assert st["queue_depth_requests"] == 2
    assert st["queue_depth_rows"] == 20
    # drain what was accepted: zero loss even for this half-started use
    eng.start()
    eng.stop(drain=True)
    assert eng.stats.as_dict()["completed"] == 2


def test_deadline_shed_before_dispatch_and_ema_reject(served):
    from transmogrifai_tpu.serving import (DeadlineExpired,
                                           DeadlineUnmeetable,
                                           EngineConfig, ServingEngine)

    model, ds, _ = served
    with ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1),
                       config=EngineConfig(max_wait_ms=0.5)) as eng:
        backend = eng.registry.get().backend
        real_run = backend.run
        gate = threading.Event()

        def slow_run(n, vals):
            gate.wait(5.0)          # hold the dispatcher mid-batch
            return real_run(n, vals)

        backend.run = slow_run
        try:
            f1 = eng.submit(_slice(ds, 0, 5))            # occupies device
            time.sleep(0.05)                              # let it dispatch
            f2 = eng.submit(_slice(ds, 0, 5), deadline_ms=30.0)
            time.sleep(0.2)       # f2's deadline expires while queued
        finally:
            gate.set()
        assert f1.result(30) is not None
        with pytest.raises(DeadlineExpired):
            f2.result(30)
        st = eng.stats.as_dict()
        assert st["shed_expired"] == 1
        assert st["completed"] == 1

        # EMA rejection: a deadline far below the observed service time
        # is rejected at submit (the EMA has samples by now)
        assert eng.admission.ema.estimate(1) is not None
        with pytest.raises(DeadlineUnmeetable):
            eng.submit(_slice(ds, 0, 5), deadline_ms=1e-3)
        assert eng.stats.as_dict()["rejected_predicted_late"] == 1


def test_engine_closed_and_nondrain_stop(served):
    from transmogrifai_tpu.serving import (EngineClosed, EngineConfig,
                                           ServingEngine)

    model, ds, _ = served
    eng = ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1),
                        config=EngineConfig(max_wait_ms=200.0))
    eng._accepting = True
    f = eng.submit(_slice(ds, 0, 4))
    eng.stop(drain=False)
    with pytest.raises(EngineClosed):
        f.result(5)
    with pytest.raises(EngineClosed):
        eng.submit(_slice(ds, 0, 4))
    assert eng.stats.as_dict()["failed"] == 1
    assert eng.cancel_event.is_set()


def test_cancelled_future_does_not_kill_dispatcher(served):
    """A caller cancelling its returned Future pre-dispatch must not
    crash the dispatcher thread (InvalidStateError on set_result) —
    the cancelled request drops out, its rows never reach the device,
    and every other caller still gets results."""
    from transmogrifai_tpu.serving import EngineConfig, ServingEngine

    model, ds, _ = served
    eng = ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1),
                        config=EngineConfig(max_wait_ms=100.0))
    eng._accepting = True            # queue before the dispatcher runs
    f1 = eng.submit(_slice(ds, 0, 4))
    f2 = eng.submit(_slice(ds, 0, 6))
    assert f1.cancel()               # still PENDING: cancel wins
    eng.start()
    got = f2.result(30)              # survivor completes normally
    assert next(iter(got.values())).shape[0] == 6
    assert eng.live()                # dispatcher did NOT die
    st = eng.stats.as_dict()
    assert st["cancelled"] == 1
    assert st["completed"] == 1
    # engine still serves new traffic after the cancel
    assert eng.score(_slice(ds, 0, 3), timeout=30) is not None
    eng.stop()
    # exactly-one-terminal-counter: submitted == completed + failed +
    # shed + cancelled (a cancelled request must not double-count)
    st = eng.stats.as_dict()
    assert st["submitted"] == (st["completed"] + st["failed"]
                               + st["shed_expired"] + st["cancelled"])


def test_results_own_their_memory(served):
    """Returned arrays never alias the bucket-padded or coalesced batch
    buffers: a retained 1-row result must not pin a top-bucket-sized
    backing array."""
    from transmogrifai_tpu.serving import EngineConfig, ServingEngine

    model, ds, _ = served
    with ServingEngine(model, buckets=(1024,),
                       warm_sample=_slice(ds, 0, 1),
                       config=EngineConfig(max_wait_ms=20.0)) as eng:
        solo = eng.score(_slice(ds, 0, 1), timeout=60)       # 1-req batch
        f1 = eng.submit(_slice(ds, 0, 2))
        f2 = eng.submit(_slice(ds, 0, 2))
        multi = f1.result(60)
        f2.result(60)
        for res in (solo, multi):
            for v in res.values():
                assert np.asarray(v).base is None            # owns memory


def test_ema_latency_unit():
    from transmogrifai_tpu.serving import EmaLatency

    ema = EmaLatency(alpha=0.5)
    assert ema.estimate(100) is None      # optimistic cold start
    ema.update(100, 0.1)
    est = ema.estimate(100)
    assert est == pytest.approx(0.1 + 100 * 0.001)
    ema.update(100, 0.2)                  # EMA moves toward new sample
    assert ema.estimate(0) == pytest.approx(0.15)
    with pytest.raises(ValueError):
        EmaLatency(alpha=0.0)


# ---------------------------------------------------------------------------
# satellite: ScoringStats.snapshot_seq — torn-read detection, lock-free-ish
# ---------------------------------------------------------------------------

def test_scoring_stats_snapshot_seq_monotonic_under_contention():
    """as_dict() snapshots carry a monotonic snapshot_seq; equal seqs
    imply identical snapshots; the read path never blocks on writer
    churn (bounded wall time while a writer hammers the lock)."""
    from transmogrifai_tpu.profiling import ScoringStats

    stats = ScoringStats()
    stop = threading.Event()

    def writer():
        b = 0
        while not stop.is_set():
            stats.note_batch(64, 60)
            b += 1
        stats.note_compile(64)

    t = threading.Thread(target=writer)
    t.start()
    try:
        t0 = time.perf_counter()
        snaps = [stats.as_dict() for _ in range(200)]
        elapsed = time.perf_counter() - t0
    finally:
        stop.set()
        t.join()
    assert elapsed < 5.0                      # contention-free read path
    seqs = [s["snapshot_seq"] for s in snaps]
    assert seqs == sorted(seqs)               # monotonic non-decreasing
    for a, b in zip(snaps, snaps[1:]):
        if a["snapshot_seq"] == b["snapshot_seq"]:
            assert a == b                     # equal seq => no torn read
    final = stats.as_dict()
    assert final["snapshot_seq"] >= seqs[-1]
    assert final["total_rows"] == 60 * final["per_bucket"]["64"]["batches"]


def test_engine_status_exposes_snapshot_seq(served):
    from transmogrifai_tpu.serving import ServingEngine

    model, ds, _ = served
    with ServingEngine(model, buckets=(32,),
                       warm_sample=_slice(ds, 0, 1)) as eng:
        eng.score(_slice(ds, 0, 5), timeout=60)
        st = eng.status()
        assert st["engine"]["snapshot_seq"] > 0
        (vname,) = st["scoring"].keys()
        assert st["scoring"][vname]["snapshot_seq"] > 0
        seq0 = st["scoring"][vname]["snapshot_seq"]
        eng.score(_slice(ds, 0, 5), timeout=60)
        assert eng.status()["scoring"][vname]["snapshot_seq"] > seq0


# ---------------------------------------------------------------------------
# satellite: score_stream cancel_event
# ---------------------------------------------------------------------------

def test_score_stream_cancel_event_aborts_promptly(served):
    """Setting cancel_event stops an in-flight stream in O(one chunk):
    the producer stops being pulled (far short of the full stream) and
    the consumer raises StreamCancelled instead of draining."""
    from transmogrifai_tpu.io.stream import StreamCancelled

    model, ds, _ = served
    scorer = model.compile_scoring(buckets=(32,))
    cancel = threading.Event()
    produced = {"n": 0}
    total = 500

    def chunks():
        for _ in range(total):
            produced["n"] += 1
            yield _slice(ds, 0, 8)

    got = 0
    with pytest.raises(StreamCancelled):
        for out in scorer.score_stream(chunks(), cancel_event=cancel):
            got += 1
            if got == 3:
                cancel.set()
    assert got >= 3
    assert produced["n"] < total      # producer did NOT drain

    # inline (host_thread=False) path honors the event too
    cancel2 = threading.Event()
    cancel2.set()
    with pytest.raises(StreamCancelled):
        list(model.compile_scoring(buckets=(32,)).score_stream(
            chunks(), host_thread=False, cancel_event=cancel2))


def test_host_prefetch_cancel_event():
    from transmogrifai_tpu.io.stream import StreamCancelled, host_prefetch

    cancel = threading.Event()
    pulled = {"n": 0}

    def src():
        for i in range(10_000):
            pulled["n"] += 1
            yield i

    it = host_prefetch(src(), buffer_size=2, cancel_event=cancel)
    assert next(it) == 0
    cancel.set()
    with pytest.raises(StreamCancelled):
        for _ in it:
            pass
    time.sleep(0.05)
    assert pulled["n"] < 10_000


# ---------------------------------------------------------------------------
# registry: artifacts, manifest, portable backend
# ---------------------------------------------------------------------------

def test_registry_export_manifest_roundtrip(served, served_v2, tmp_path):
    """export_registry_version writes version dirs + registry.json;
    ModelRegistry.from_dir serves the manifest's default; the engine
    scores identically from the loaded registry."""
    from transmogrifai_tpu.portable_export import (export_registry_version,
                                                   write_registry_manifest)
    from transmogrifai_tpu.serving import ModelRegistry, ServingEngine

    model1, ds, _ = served
    model2, _, _ = served_v2
    root = str(tmp_path / "registry")
    export_registry_version(model1, root, "2026-08-01", buckets=(32, 64))
    files = export_registry_version(model2, root, "2026-08-02",
                                    buckets=(32, 64))
    assert os.path.exists(files["registry.json"])
    with open(files["registry.json"]) as f:
        doc = json.load(f)
    assert doc["default"] == "2026-08-02"
    assert set(doc["versions"]) == {"2026-08-01", "2026-08-02"}
    assert doc["versions"]["2026-08-01"]["kind"] == "workflow"

    reg = ModelRegistry.from_dir(root, buckets=(32, 64))
    assert reg.default_version == "2026-08-02"
    ref = model2.compile_scoring().score_arrays(_slice(ds, 0, 20))
    with ServingEngine(registry=reg) as eng:
        got = eng.score(_slice(ds, 0, 20), timeout=60)
    for k in ref:
        assert np.array_equal(ref[k], got[k])

    # re-index keeps an existing default when it still exists
    write_registry_manifest(root)
    with open(os.path.join(root, "registry.json")) as f:
        assert json.load(f)["default"] == "2026-08-02"
    # explicit unknown default fails loudly
    with pytest.raises(ValueError):
        write_registry_manifest(root, default="nope")
    # a canary exported with set_default=False must not win the
    # fallback on a reset root just by sorting last
    os.remove(os.path.join(root, "registry.json"))
    export_registry_version(model1, root, "2026-09-09-canary",
                            buckets=(32, 64), set_default=False)
    with open(os.path.join(root, "registry.json")) as f:
        assert json.load(f)["default"] == "2026-08-02"


def test_portable_backend_through_engine(served, tmp_path):
    """A portable-export artifact (numpy-only, no jax) serves through
    the same engine; results match the portable runtime exactly."""
    from transmogrifai_tpu import portable
    from transmogrifai_tpu.serving import ServingEngine

    model, ds, pred_name = served
    art = str(tmp_path / "artifact")
    model.export_portable(art, buckets=(32, 64))
    pm = portable.load(art)
    cols = {f"x{i}": np.asarray(ds.column(f"x{i}")[:15], np.float64)
            for i in range(5)}
    ref = pm.score_columns(cols)

    with ServingEngine(art, buckets=(32, 64)) as eng:
        assert eng.registry.get().backend.kind == "portable"
        got = eng.score(dict(cols), timeout=60)
    for k in ref:
        assert np.array_equal(ref[k], got[k])


def test_mixed_dtype_requests_never_promote_each_other(served, tmp_path):
    """Two concurrent requests supplying the SAME column as float vs int
    must not be concatenated into one promoted batch (int ids would
    corrupt, and both callers' results would drift) — they score in
    separate dtype-homogeneous groups, each exact."""
    from transmogrifai_tpu import portable
    from transmogrifai_tpu.serving import EngineConfig, ServingEngine

    model, ds, _ = served
    art = str(tmp_path / "artifact")
    model.export_portable(art, buckets=(32,))
    pm = portable.load(art)
    cols_f = {f"x{i}": np.asarray(ds.column(f"x{i}")[:4], np.float64)
              for i in range(5)}
    cols_i = {f"x{i}": np.arange(1, 5, dtype=np.int64) for i in range(5)}
    ref_f = pm.score_columns(cols_f)
    ref_i = pm.score_columns(cols_i)

    eng = ServingEngine(art, config=EngineConfig(max_wait_ms=100.0))
    eng._accepting = True            # queue both BEFORE dispatch
    f1 = eng.submit(dict(cols_f))
    f2 = eng.submit(dict(cols_i))
    eng.start()
    got_f, got_i = f1.result(30), f2.result(30)
    for k in ref_f:
        assert np.array_equal(ref_f[k], got_f[k])
        assert np.array_equal(ref_i[k], got_i[k])
    # two groups dispatched, not one promoted batch
    assert eng.stats.as_dict()["batches"] == 2
    eng.stop()


def test_portable_ragged_request_fails_at_submit(served, tmp_path):
    from transmogrifai_tpu.serving import ServingEngine

    model, ds, _ = served
    art = str(tmp_path / "artifact")
    model.export_portable(art, buckets=(32,))
    with ServingEngine(art) as eng:
        bad = {f"x{i}": np.zeros(3 if i else 4) for i in range(5)}
        with pytest.raises(ValueError, match="share one length"):
            eng.submit(bad)


def test_engine_restart_clears_cancel_event(served):
    from transmogrifai_tpu.serving import ServingEngine

    model, ds, _ = served
    eng = ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1))
    eng.start()
    eng.stop()
    assert eng.cancel_event.is_set()
    eng.start()
    assert not eng.cancel_event.is_set()    # restart: fresh signal
    assert eng.score(_slice(ds, 0, 5), timeout=30) is not None
    eng.stop()


def test_registry_from_dir_lazy_loads_history(served, served_v2, tmp_path):
    """Only the default version loads at from_dir time; deploy history
    loads on first acquire."""
    from transmogrifai_tpu.portable_export import export_registry_version
    from transmogrifai_tpu.serving import ModelRegistry

    model1, ds, _ = served
    model2, _, _ = served_v2
    root = str(tmp_path / "registry")
    export_registry_version(model1, root, "2026-07-01", buckets=(32,))
    export_registry_version(model2, root, "2026-08-01", buckets=(32,))
    reg = ModelRegistry.from_dir(root)
    info = reg.versions()
    assert info["2026-08-01"]["loaded"]          # default: eager
    assert not info["2026-07-01"]["loaded"]      # history: lazy
    # the exported scoreBuckets (32,) are authoritative — NOT the
    # 10-bucket default set from_dir's buckets=True would imply
    assert reg.get("2026-08-01").backend.buckets == (32,)
    with reg.acquire("2026-07-01") as (_, backend):   # loads on demand
        (ref,) = model1.compile_scoring().score_arrays(
            _slice(ds, 0, 5)).values()
        n, vals = backend.prepare(_slice(ds, 0, 5))
        (got,) = backend.run(n, vals).values()
        assert np.array_equal(ref, got)
    assert reg.versions()["2026-07-01"]["loaded"]


def test_registry_retire_guards(served):
    from transmogrifai_tpu.serving import ModelRegistry

    model, ds, _ = served
    reg = ModelRegistry()
    reg.register("a", model, buckets=(32,), warm=False)
    with pytest.raises(ValueError):        # cannot retire the default
        reg.retire("a")
    with pytest.raises(ValueError):        # duplicate name
        reg.register("a", model, warm=False)
    with pytest.raises(KeyError):
        reg.get("missing")
    reg.register("b", model, buckets=(32,), warm=False, make_default=True)
    assert reg.set_default("b") == "b"     # idempotent flip returns prev
    assert reg.retire("a", drain_timeout=5.0)
    assert reg.get("a").released
    with pytest.raises(RuntimeError):      # released backend unusable
        with reg.acquire("a"):
            pass


# ---------------------------------------------------------------------------
# health endpoints
# ---------------------------------------------------------------------------

def test_health_server_endpoints(served):
    import urllib.error
    import urllib.request

    from transmogrifai_tpu.serving import HealthServer, ServingEngine

    model, ds, _ = served
    eng = ServingEngine(model, buckets=(32,), warm_sample=_slice(ds, 0, 1))
    eng.start()
    hs = HealthServer(eng, port=0).start()
    base = f"http://127.0.0.1:{hs.port}"
    try:
        eng.score(_slice(ds, 0, 5), timeout=60)
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as r:
            assert json.loads(r.read())["live"] is True
        with urllib.request.urlopen(f"{base}/readyz", timeout=5) as r:
            assert json.loads(r.read())["ready"] is True
        with urllib.request.urlopen(f"{base}/statusz", timeout=5) as r:
            doc = json.loads(r.read())
        assert doc["engine"]["completed"] == 1
        assert doc["default_version"] == "v1"
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/nope", timeout=5)
        assert exc.value.code == 404
        eng.stop()
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{base}/readyz", timeout=5)
        assert exc.value.code == 503
    finally:
        hs.stop()
        eng.stop()


# ---------------------------------------------------------------------------
# CLI --engine mode
# ---------------------------------------------------------------------------

def test_serve_cli_engine_mode(served, tmp_path):
    from transmogrifai_tpu.cli import main as cli_main

    model, ds, pred_name = served
    model_dir = str(tmp_path / "model")
    model.save(model_dir)
    in_jsonl = str(tmp_path / "requests.jsonl")
    reqs = []
    with open(in_jsonl, "w") as f:
        for n in (1, 7, 3, 12, 5):
            cols = {f"x{i}": [None if np.isnan(v) else float(v)
                              for v in ds.column(f"x{i}")[:n]]
                    for i in range(5)}
            reqs.append(n)
            f.write(json.dumps({"columns": cols}) + "\n")
        # single-row scalar shape also accepted
        f.write(json.dumps({f"x{i}": 0.5 for i in range(5)}) + "\n")
        reqs.append(1)
    out_jsonl = str(tmp_path / "responses.jsonl")
    stats_json = str(tmp_path / "engine_stats.json")
    rc = cli_main(["serve", "--model", model_dir, "--input", in_jsonl,
                   "--output", out_jsonl, "--engine", "--clients", "4",
                   "--buckets", "32,64", "--stats-json", stats_json])
    assert rc == 0
    with open(stats_json) as f:
        summary = json.load(f)
    assert summary["requests"] == len(reqs)
    assert summary["errors"] == 0
    assert summary["rows"] == sum(reqs)
    assert summary["status"]["engine"]["completed"] == len(reqs)
    with open(out_jsonl) as f:
        lines = [json.loads(l) for l in f]
    assert [l["id"] for l in lines] == list(range(len(reqs)))
    naive = model.compile_scoring()
    for i, n in enumerate(reqs[:-1]):
        ref = naive.score_arrays(_slice(ds, 0, n))[pred_name]
        got = np.asarray(lines[i]["results"][pred_name])
        np.testing.assert_allclose(got, ref, atol=1e-6)


def test_request_columns_shapes():
    from transmogrifai_tpu.cli import _request_columns

    assert _request_columns({"columns": {"a": [1, 2]}}) == {"a": [1, 2]}
    assert _request_columns({"a": [1, 2], "b": [3, 4]}) == {"a": [1, 2],
                                                           "b": [3, 4]}
    assert _request_columns({"a": 1.5, "b": 2.5}) == {"a": [1.5],
                                                      "b": [2.5]}
    assert _request_columns([{"a": 1}, {"a": 2}]) == {"a": [1, 2]}
    with pytest.raises(ValueError):
        _request_columns([])
    with pytest.raises(ValueError):
        _request_columns("nope")


# ---------------------------------------------------------------------------
# stress (slow tier): sustained concurrency + swap + deadlines
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_engine_stress_sustained_mixed_traffic(served, served_v2):
    """Sustained 16-thread mixed traffic with a mid-run hot-swap and a
    deadline-carrying minority: every accepted request resolves (result
    or loud shed), nothing blends versions, counters reconcile."""
    from transmogrifai_tpu.serving import (DeadlineExpired, EngineConfig,
                                           RejectedError, ServingEngine)

    model1, ds, _ = served
    model2, _, _ = served_v2
    sizes = (1, 4, 9, 17, 33, 50)
    ref1 = {n: model1.compile_scoring().score_arrays(_slice(ds, 0, n))
            for n in sizes}
    ref2 = {n: model2.compile_scoring().score_arrays(_slice(ds, 0, n))
            for n in sizes}
    cfg = EngineConfig(max_wait_ms=1.0, max_queue_rows=4096)
    with ServingEngine(model1, buckets=(32, 64), version="v1",
                       warm_sample=_slice(ds, 0, 1), config=cfg) as eng:
        stop = threading.Event()
        counts = {"ok": 0, "shed": 0, "rejected": 0}
        errors = []
        lock = threading.Lock()

        def client(seed):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                n = int(rng.choice(sizes))
                deadline = 200.0 if rng.random() < 0.25 else None
                try:
                    got = eng.score(_slice(ds, 0, n), timeout=60,
                                    deadline_ms=deadline)
                except (DeadlineExpired, RejectedError) as e:
                    with lock:
                        counts["shed" if isinstance(e, DeadlineExpired)
                               else "rejected"] += 1
                    continue
                except Exception as e:      # pragma: no cover
                    errors.append(e)
                    return
                (gv,) = got.values()
                (r1,) = ref1[n].values()
                (r2,) = ref2[n].values()
                if not (np.array_equal(r1, gv) or np.array_equal(r2, gv)):
                    errors.append(AssertionError(f"blend at n={n}"))
                    return
                with lock:
                    counts["ok"] += 1

        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(16)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(1.0)
        eng.swap("v2", model2, buckets=(32, 64),
                 warm_sample=_slice(ds, 0, 1))
        time.sleep(1.0)
        stop.set()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        assert not errors
        st = eng.status()
        assert counts["ok"] >= 16          # real sustained traffic
        assert st["engine"]["completed"] == counts["ok"]
        assert st["engine"]["shed_expired"] == counts["shed"]
        assert (st["engine"]["rejected_queue_full"]
                + st["engine"]["rejected_predicted_late"]
                ) == counts["rejected"]
        assert (st["engine"]["submitted"]
                == counts["ok"] + counts["shed"])
        assert st["engine"]["wait_p99_ms"] >= 0.0
        assert elapsed < 60
