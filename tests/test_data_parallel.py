"""Data-parallel layer tests on the forced 8-device CPU mesh.

Reference analogs: the reference has no direct test (Spark local[*]
covers DP implicitly); here the sharded statistics must match the
single-device computation exactly and the SanityChecker must produce
identical decisions either way.
"""
import jax
import numpy as np
import pytest

from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops.sanity_checker import (SanityChecker,
                                                  compute_statistics)
from transmogrifai_tpu.parallel import (data_mesh, sharded_contingency,
                                        sharded_score, sharded_statistics)
from transmogrifai_tpu.testkit import TestFeatureBuilder


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return data_mesh()


def test_sharded_statistics_match_single_device(mesh):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1000, 12)).astype(np.float32)
    X[:, 3] = 0.0  # constant column exercises the std guard
    y = (rng.random(1000) > 0.5).astype(np.float32)
    ref = compute_statistics(X, y)
    got = sharded_statistics(X, y, mesh)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k, equal_nan=True)


def test_sharded_statistics_uneven_rows(mesh):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1003, 5)).astype(np.float32)  # not divisible by 8
    y = rng.normal(size=1003).astype(np.float32)
    ref = compute_statistics(X, y)
    got = sharded_statistics(X, y, mesh)
    np.testing.assert_allclose(got["mean"], ref["mean"], rtol=1e-4)
    np.testing.assert_allclose(got["spearman"], ref["spearman"],
                               rtol=1e-3, atol=1e-4)


def test_sharded_contingency(mesh):
    rng = np.random.default_rng(2)
    g = (rng.random((800, 4)) > 0.7).astype(np.float32)
    yo = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 800)]
    t = sharded_contingency(g, yo, mesh)
    np.testing.assert_allclose(t, g.T @ yo, rtol=1e-5)


def test_sharded_score_matches_local(mesh):
    from transmogrifai_tpu.models.base import MODEL_FAMILIES
    import jax.numpy as jnp

    fam = MODEL_FAMILIES["LogisticRegression"]
    rng = np.random.default_rng(3)
    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = (rng.random(512) > 0.5).astype(np.float32)
    params = fam.fit_kernel(jnp.asarray(X), jnp.asarray(y),
                            jnp.ones(512, jnp.float32),
                            {"regParam": jnp.float32(0.01),
                             "elasticNetParam": jnp.float32(0.0)}, 2)
    local = np.asarray(fam.predict_kernel(params, jnp.asarray(X), 2))
    dist = sharded_score(fam.predict_kernel, jax.tree.map(np.asarray, params),
                         X, mesh)
    np.testing.assert_allclose(dist, local, rtol=1e-5, atol=1e-6)


def test_sanity_checker_distributed_equals_local(mesh):
    rng = np.random.default_rng(4)
    n = 400
    y = (rng.random(n) > 0.5).astype(float)
    vecs = np.stack([rng.normal(size=n),            # fine
                     np.zeros(n),                   # low variance -> drop
                     y * 2 - 1 + rng.normal(0, 1e-4, n),  # leaky -> drop
                     rng.normal(size=n)], axis=1)
    ds, feats = TestFeatureBuilder.of(
        {"label": (ft.RealNN, y.tolist()),
         "vec": (ft.OPVector, [tuple(r) for r in vecs])}, response="label")

    local = SanityChecker().set_input(feats["label"], feats["vec"]).fit(ds)
    dist = SanityChecker(mesh=mesh).set_input(
        feats["label"], feats["vec"]).fit(ds)
    assert local.summary["dropped"] == dist.summary["dropped"]
    assert local.params["keep_indices"] == dist.params["keep_indices"]
