"""Data-parallel layer tests on the forced 8-device CPU mesh.

Reference analogs: the reference has no direct test (Spark local[*]
covers DP implicitly); here the sharded statistics must match the
single-device computation exactly and the SanityChecker must produce
identical decisions either way.
"""
import jax
import numpy as np
import pytest

from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops.sanity_checker import (SanityChecker,
                                                  compute_statistics)
from transmogrifai_tpu.parallel import (data_mesh, sharded_contingency,
                                        sharded_score, sharded_statistics)
from transmogrifai_tpu.testkit import TestFeatureBuilder


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return data_mesh()


def test_sharded_statistics_match_single_device(mesh):
    rng = np.random.default_rng(0)
    X = rng.normal(size=(1000, 12)).astype(np.float32)
    X[:, 3] = 0.0  # constant column exercises the std guard
    y = (rng.random(1000) > 0.5).astype(np.float32)
    ref = compute_statistics(X, y)
    got = sharded_statistics(X, y, mesh)
    for k in ref:
        np.testing.assert_allclose(got[k], ref[k], rtol=1e-4, atol=1e-5,
                                   err_msg=k, equal_nan=True)


def test_sharded_statistics_uneven_rows(mesh):
    rng = np.random.default_rng(1)
    X = rng.normal(size=(1003, 5)).astype(np.float32)  # not divisible by 8
    y = rng.normal(size=1003).astype(np.float32)
    ref = compute_statistics(X, y)
    got = sharded_statistics(X, y, mesh)
    np.testing.assert_allclose(got["mean"], ref["mean"], rtol=1e-4)
    np.testing.assert_allclose(got["spearman"], ref["spearman"],
                               rtol=1e-3, atol=1e-4)


def test_grid_map_rejects_none_leaves():
    """ADVICE r4: a None leaf would vanish from the spec pytree and blow
    up deep inside sharding — the entry must reject it by name."""
    import jax.numpy as jnp

    from transmogrifai_tpu.parallel.mesh import grid_map

    with pytest.raises(ValueError, match="None leaves"):
        grid_map(lambda item: item[0], (jnp.ones((8, 4)), None))


def test_spearman_average_ranks_match_scipy_on_ties():
    """VERDICT r4 weak #7: tie-averaged ranks, not ordinal — verified
    against scipy.spearmanr on heavily tied indicator-like columns."""
    from scipy.stats import spearmanr

    rng = np.random.default_rng(7)
    n = 500
    X = np.stack([
        (rng.random(n) > 0.8).astype(np.float32),      # binary indicator
        rng.integers(0, 3, n).astype(np.float32),       # 3-level categorical
        rng.normal(size=n).astype(np.float32),          # no ties
        np.round(rng.normal(size=n), 1).astype(np.float32),  # many ties
        np.zeros(n, np.float32),                        # constant (guarded)
    ], axis=1)
    y = (X[:, 0] + rng.normal(0, 0.5, n) > 0.5).astype(np.float32)
    got = compute_statistics(X, y)["spearman"]
    for j in range(4):
        want = spearmanr(X[:, j], y).statistic
        np.testing.assert_allclose(got[j], want, atol=1e-6,
                                   err_msg=f"column {j}")


def test_sharded_contingency(mesh):
    rng = np.random.default_rng(2)
    g = (rng.random((800, 4)) > 0.7).astype(np.float32)
    yo = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 800)]
    t = sharded_contingency(g, yo, mesh)
    np.testing.assert_allclose(t, g.T @ yo, rtol=1e-5)


def test_sharded_score_matches_local(mesh):
    from transmogrifai_tpu.models.base import MODEL_FAMILIES
    import jax.numpy as jnp

    fam = MODEL_FAMILIES["LogisticRegression"]
    rng = np.random.default_rng(3)
    X = rng.normal(size=(512, 8)).astype(np.float32)
    y = (rng.random(512) > 0.5).astype(np.float32)
    params = fam.fit_kernel(jnp.asarray(X), jnp.asarray(y),
                            jnp.ones(512, jnp.float32),
                            {"regParam": jnp.float32(0.01),
                             "elasticNetParam": jnp.float32(0.0)}, 2)
    local = np.asarray(fam.predict_kernel(params, jnp.asarray(X), 2))
    dist = sharded_score(fam.predict_kernel, jax.tree.map(np.asarray, params),
                         X, mesh)
    np.testing.assert_allclose(dist, local, rtol=1e-5, atol=1e-6)


def test_sanity_checker_distributed_equals_local(mesh):
    rng = np.random.default_rng(4)
    n = 400
    y = (rng.random(n) > 0.5).astype(float)
    vecs = np.stack([rng.normal(size=n),            # fine
                     np.zeros(n),                   # low variance -> drop
                     y * 2 - 1 + rng.normal(0, 1e-4, n),  # leaky -> drop
                     rng.normal(size=n)], axis=1)
    ds, feats = TestFeatureBuilder.of(
        {"label": (ft.RealNN, y.tolist()),
         "vec": (ft.OPVector, [tuple(r) for r in vecs])}, response="label")

    local = SanityChecker().set_input(feats["label"], feats["vec"]).fit(ds)
    dist = SanityChecker(mesh=mesh).set_input(
        feats["label"], feats["vec"]).fit(ds)
    assert local.summary["dropped"] == dist.summary["dropped"]
    assert local.params["keep_indices"] == dist.params["keep_indices"]


# ---------------------------------------------------------------------------
# 2-D (grid x data) mesh: GSPMD row sharding must match 1-D grid sharding
# (reference: Rabit/treeAggregate histogram+gradient allreduce parity)
# ---------------------------------------------------------------------------

def _cv_metrics(fam_name, mesh, n=531, d=7):
    import jax.numpy as jnp
    from transmogrifai_tpu.models.base import MODEL_FAMILIES
    from transmogrifai_tpu.models.tuning import OpCrossValidation

    rng = np.random.default_rng(7)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = np.linspace(-1, 1, d).astype(np.float32)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ beta)))).astype(np.float32)
    fam = MODEL_FAMILIES[fam_name]
    cv = OpCrossValidation(n_folds=3, metric="auroc")
    res = cv.validate(fam, fam.make_grid(), X, y,
                      np.ones(n, np.float32), 2, mesh=mesh)
    return res


def test_grid_by_data_mesh_matches_1d():
    from transmogrifai_tpu.parallel.mesh import get_mesh, get_mesh_2d

    res_1d = _cv_metrics("LogisticRegression", get_mesh())
    mesh2d = get_mesh_2d()  # 8 devices -> (2 grid, 4 data)
    assert mesh2d.shape["data"] > 1
    res_2d = _cv_metrics("LogisticRegression", mesh2d)
    np.testing.assert_allclose(res_2d.grid_metrics, res_1d.grid_metrics,
                               rtol=1e-3, atol=1e-4)
    assert res_2d.best_index == res_1d.best_index


@pytest.mark.slow
def test_grid_by_data_mesh_trees_match(monkeypatch):
    """Histogram-GBDT under row sharding (the Rabit-parity claim).

    The e2e tolerance is loose-ish on purpose: the data-axis psum changes
    float summation order, and greedy split selection is discontinuous at
    near-tie gains, so boosted metrics can drift a few 1e-3 — exactly like
    XGBoost across different Rabit world sizes. Exact parity of the
    aggregation itself is asserted at histogram level below.

    Both meshes must run the SAME formulation: the 2-D data-sharded mesh
    always uses the generic vmap path, so pin it for the 1-D side too
    (the folded path's global sketch is compared against the generic
    path in test_grid_fold.py, not here).
    """
    from transmogrifai_tpu.parallel.mesh import get_mesh, get_mesh_2d

    monkeypatch.setenv("TM_TREE_GRID_FOLD", "0")
    res_1d = _cv_metrics("GBTClassifier", get_mesh(), n=322, d=5)
    res_2d = _cv_metrics("GBTClassifier", get_mesh_2d(), n=322, d=5)
    np.testing.assert_allclose(res_2d.grid_metrics, res_1d.grid_metrics,
                               atol=1e-2)


def test_row_sharded_histogram_exact():
    """The histogram matmul (the op Rabit allreduces in XGBoost) under
    "data" row sharding matches the unsharded sum to float tolerance."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from transmogrifai_tpu.models.trees import bin_data, quantile_bin_edges
    from transmogrifai_tpu.parallel.mesh import get_mesh_2d

    rng = np.random.default_rng(11)
    n, d, B = 1024, 6, 16
    X = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.random(n).astype(np.float32)
    stats = rng.normal(size=(n, 3)).astype(np.float32)
    edges = quantile_bin_edges(jnp.asarray(X), B, jnp.asarray(w))
    bins = bin_data(jnp.asarray(X), edges)
    Z = np.eye(B, dtype=np.float32)[np.asarray(bins)].reshape(n, d * B)
    ref = (stats * w[:, None]).T @ Z

    mesh = get_mesh_2d()
    sh = NamedSharding(mesh, P("data"))

    def hist(stats_j, w_j, Z_j):
        return (stats_j * w_j[:, None]).T @ Z_j

    got = jax.jit(hist, in_shardings=(sh, sh, sh))(stats, w, Z)
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-4, atol=1e-4)


def test_dispatch_collect_parity_and_async():
    """dispatch() must not block; collect() must equal validate()."""
    from transmogrifai_tpu.models.base import MODEL_FAMILIES
    from transmogrifai_tpu.models.tuning import OpCrossValidation

    rng = np.random.default_rng(3)
    n, d = 200, 4
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    w = np.ones(n, np.float32)
    cv = OpCrossValidation(n_folds=3, metric="auroc")
    pendings = []
    for name in ("LogisticRegression", "NaiveBayes"):
        fam = MODEL_FAMILIES[name]
        pendings.append(cv.dispatch(fam, fam.make_grid(), X, y, w, 2))
    results = [cv.collect(p) for p in pendings]
    for p, r in zip(pendings, results):
        direct = cv.validate(MODEL_FAMILIES[p.family], p.grid, X, y, w, 2)
        np.testing.assert_allclose(r.grid_metrics, direct.grid_metrics,
                                   rtol=1e-5)
        assert r.best_index == direct.best_index
