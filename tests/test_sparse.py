"""Criteo-scale sparse path: hashing vectorizer, sparse LR, streaming.

Reference analogs: OPCollectionHashingVectorizerTest / SmartTextVectorizer
hashing-branch tests; the model side has no direct reference test (mllib
LR over sparse vectors is tested upstream in Spark) so the contract here
is learnability + dense-path agreement + streaming/in-memory parity.
"""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.models.sparse import (
    SparseLogisticRegression, fit_sparse_lr, fit_sparse_lr_streaming,
    predict_sparse_lr, validate_sparse_grid)
from transmogrifai_tpu.ops.sparse import SparseHashingVectorizer, hash_tokens
from transmogrifai_tpu.ops.hashing import murmur3_32


def _ctr_data(rng, n, n_cat=6, card=50, d_num=4, buckets=1 << 12):
    """Synthetic CTR: label depends on two categorical columns + numerics."""
    cats = {f"c{j}": rng.integers(0, card, n) for j in range(n_cat)}
    nums = rng.normal(size=(n, d_num)).astype(np.float32)
    logits = ((cats["c0"] % 7 < 3).astype(np.float32) * 1.5
              - (cats["c1"] % 5 < 2).astype(np.float32) * 1.2
              + nums[:, 0] * 0.8)
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float32)
    # hash like the vectorizer would
    idx = np.zeros((n, n_cat), np.int32)
    for j, (name, col) in enumerate(sorted(cats.items())):
        toks = [f"{name}|{v}" for v in col]
        idx[:, j] = hash_tokens(toks, buckets, 42)
    return idx, nums, y


def test_hash_tokens_native_matches_python():
    toks = [f"f|{i}" for i in range(200)] + ["f|__null__", "g|hello world"]
    got = hash_tokens(toks, 4096, 42)
    ref = np.asarray([murmur3_32(t.encode(), 42) % 4096 for t in toks],
                     np.int32)
    np.testing.assert_array_equal(got, ref)


def test_hash_column_dedup_bit_identical(rng):
    """_hash_column (unique-dedup fast path) must produce EXACTLY the
    per-row token hashes for every column shape the vectorizer sees:
    strings, strings with None/'' nulls, numeric codes with NaN nulls,
    and object columns holding non-string values."""
    from transmogrifai_tpu.ops.sparse import _hash_column, _token

    B, seed = 1 << 12, 42

    def ref(values):
        return np.asarray([murmur3_32(_token("f", v).encode(), seed) % B
                           for v in values], np.int32)

    strs = np.asarray([f"v{i % 7}" for i in range(500)], dtype=object)
    strs[3] = None
    strs[10] = ""
    np.testing.assert_array_equal(
        _hash_column(strs, "f", B, seed),
        ref([None if s == "" else s for s in strs.tolist()]))

    nums = rng.integers(0, 50, 300).astype(np.float64)
    nums[7] = np.nan
    nums[8] = np.nan
    np.testing.assert_array_equal(
        _hash_column(nums, "f", B, seed),
        ref([None if np.isnan(v) else int(v) for v in nums]))

    mixed = np.asarray([3.5, None, "x", 2], dtype=object)
    np.testing.assert_array_equal(_hash_column(mixed, "f", B, seed),
                                  ref([3.5, None, "x", 2]))

    # the no-native string branch (unique-dedup over fixed-width
    # unicode) must agree bit-for-bit with the native batch branch
    import transmogrifai_tpu.ops.sparse as sp
    import unittest.mock as mock
    with mock.patch.object(sp, "hash_tokens", wraps=sp.hash_tokens) as ht, \
            mock.patch("transmogrifai_tpu.native.available",
                       return_value=False):
        got = _hash_column(strs, "f", B, seed)
        assert len(ht.call_args_list[0].args[0]) <= 8  # hashed uniques only
    np.testing.assert_array_equal(
        got, ref([None if s == "" else s for s in strs.tolist()]))


def test_sparse_hashing_vectorizer_stage(rng):
    n = 40
    ds = Dataset.from_dict(
        {"a": [f"v{i % 5}" for i in range(n)],
         "b": [None if i % 7 == 0 else f"u{i % 3}" for i in range(n)],
         "k": list(range(n))},
        {"a": ft.PickList, "b": ft.PickList, "k": ft.Integral})
    fa = FeatureBuilder.of(ft.PickList, "a").from_column().as_predictor()
    fb = FeatureBuilder.of(ft.PickList, "b").from_column().as_predictor()
    fk = FeatureBuilder.of(ft.Integral, "k").from_column().as_predictor()
    st = SparseHashingVectorizer(num_buckets=1 << 10).set_input(fa, fb, fk)
    out = st.transform(ds)
    col = out.column(st.output.name)
    assert col.shape == (n, 3) and col.dtype == np.int32
    assert (col >= 0).all() and (col < 1 << 10).all()
    # same raw value -> same bucket; different features with same value
    # hash apart (per-feature token salt)
    assert col[0, 0] == col[5, 0]           # both "v0"
    # row path agrees with batch path (local scoring parity)
    row = st.transform_value(ft.PickList("v0"), ft.PickList(None),
                             ft.Integral(0))
    assert row.value[0] == col[0, 0] and row.value[1] == col[0, 1]
    assert row.value[2] == col[0, 2]


def test_sparse_lr_learns_and_beats_prior(rng):
    idx, nums, y = _ctr_data(rng, 4000)
    params = fit_sparse_lr(idx, nums, y, np.ones_like(y), 1 << 12,
                           lr=0.1, epochs=3, batch_size=512)
    probs = predict_sparse_lr(params, idx, nums)
    from transmogrifai_tpu.evaluators.functional import auroc
    import jax.numpy as jnp
    a = float(auroc(jnp.asarray(probs[:, 1]), jnp.asarray(y), None))
    assert a > 0.75, a


def test_sparse_lr_streaming_matches_in_memory(rng):
    idx, nums, y = _ctr_data(rng, 2048)
    w = np.ones_like(y)
    full = fit_sparse_lr(idx, nums, y, w, 1 << 12, lr=0.1, epochs=2,
                         batch_size=256)

    def chunks():
        for s in range(0, 2048, 512):
            sl = slice(s, s + 512)
            yield {"idx": idx[sl], "num": nums[sl], "y": y[sl], "w": w[sl]}

    stream = fit_sparse_lr_streaming(chunks, 1 << 12, nums.shape[1],
                                     lr=0.1, epochs=2, batch_size=256)
    # identical update sequence -> identical parameters
    np.testing.assert_allclose(stream["table"], full["table"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(stream["dense"], full["dense"],
                               rtol=1e-5, atol=1e-6)


def test_sparse_stage_end_to_end_and_persistence(rng, tmp_path):
    import json
    from transmogrifai_tpu.stages import stage_from_json, stage_to_json

    n = 1500
    idx, nums, y = _ctr_data(rng, n)
    ds = Dataset(
        {"y": y.astype(np.float64), "sx": idx, "nx": nums},
        {"y": ft.RealNN, "sx": ft.SparseIndices, "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column().as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    est = SparseLogisticRegression(num_buckets=1 << 12, lr=0.1, epochs=2,
                                   batch_size=256).set_input(fy, fs, fn)
    model, out = est.fit_transform(ds)
    col = out.column(model.output.name)
    assert {"prediction", "probability_1"} <= set(col[0])
    loaded = stage_from_json(json.loads(json.dumps(
        stage_to_json(model), default=lambda o: o.tolist()
        if isinstance(o, np.ndarray) else o)))
    col2 = loaded.transform(ds).column(loaded.output.name)
    assert col[3]["probability_1"] == pytest.approx(
        col2[3]["probability_1"], abs=1e-6)
    # row path parity
    row = model.transform_value(ft.RealNN(0.0),
                                ft.SparseIndices(tuple(idx[3])),
                                ft.OPVector(tuple(map(float, nums[3]))))
    assert row.value["probability_1"] == pytest.approx(
        col[3]["probability_1"], abs=1e-5)


def test_validate_sparse_grid_picks_sane(rng):
    idx, nums, y = _ctr_data(rng, 3000)
    res = validate_sparse_grid(
        idx, nums, y,
        [{"lr": 0.1, "l2": 0.0}, {"lr": 1e-5, "l2": 0.0}],
        n_buckets=1 << 12, n_folds=2, epochs=2, batch_size=512)
    assert res["best_hyper"]["lr"] == 0.1  # near-zero lr barely learns
    assert len(res["logloss"]) == 2


def test_validate_sparse_grid_streaming_matches_single_chunk(rng):
    """Selection must not depend on device residency: cutting the train
    split into small chunks (max_device_rows) gives the SAME losses as
    the one-chunk sweep — same fold hash, same update sequence."""
    idx, nums, y = _ctr_data(rng, 2000)
    grid = [{"lr": 0.1, "l2": 0.0}, {"lr": 0.05, "l2": 1e-6},
            {"family": "ftrl", "alpha": 0.1, "l1": 0.0}]
    one = validate_sparse_grid(idx, nums, y, grid, n_buckets=1 << 12,
                               n_folds=2, epochs=1, batch_size=256)
    many = validate_sparse_grid(idx, nums, y, grid, n_buckets=1 << 12,
                                n_folds=2, epochs=1, batch_size=256,
                                max_device_rows=512)
    # batch boundaries shift when chunking (each chunk pads/scans on its
    # own), so allow small numeric drift but identical ranking
    np.testing.assert_allclose(many["logloss"], one["logloss"], rtol=0.08)
    assert many["best_index"] == one["best_index"]


def test_sparse_ftrl_learns_and_l1_sparsifies(rng):
    from transmogrifai_tpu.models.sparse import fit_sparse_ftrl

    idx, nums, y = _ctr_data(rng, 4000)
    w = np.ones_like(y)
    params = fit_sparse_ftrl(idx, nums, y, w, 1 << 12, alpha=0.3,
                             epochs=3, batch_size=512)
    probs = predict_sparse_lr(params, idx, nums)   # same param shape
    from transmogrifai_tpu.evaluators.functional import auroc
    import jax.numpy as jnp
    a = float(auroc(jnp.asarray(probs[:, 1]), jnp.asarray(y), None))
    assert a > 0.75, a
    # L1 produces EXACT zeros on the table (the FTRL selling point)
    dense_nz = np.count_nonzero(params["table"])
    strong = fit_sparse_ftrl(idx, nums, y, w, 1 << 12, alpha=0.3,
                             l1=0.5, epochs=3, batch_size=512)
    assert np.count_nonzero(strong["table"]) < dense_nz


def test_sparse_ftrl_streaming_matches_in_memory(rng):
    from transmogrifai_tpu.models.sparse import (fit_sparse_ftrl,
                                                 fit_sparse_ftrl_streaming)

    idx, nums, y = _ctr_data(rng, 2048)
    w = np.ones_like(y)
    full = fit_sparse_ftrl(idx, nums, y, w, 1 << 12, alpha=0.2,
                           l1=1e-3, epochs=2, batch_size=256)

    def chunks():
        for s in range(0, 2048, 512):
            sl = slice(s, s + 512)
            yield {"idx": idx[sl], "num": nums[sl], "y": y[sl], "w": w[sl]}

    stream = fit_sparse_ftrl_streaming(chunks, 1 << 12, nums.shape[1],
                                       alpha=0.2, l1=1e-3, epochs=2,
                                       batch_size=256)
    np.testing.assert_allclose(stream["table"], full["table"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(stream["dense"], full["dense"],
                               rtol=1e-5, atol=1e-6)


def test_prefetch_to_device_preserves_order_and_values():
    from transmogrifai_tpu.io import prefetch_to_device

    chunks = [{"a": np.full((4,), i, np.float32)} for i in range(7)]
    out = list(prefetch_to_device(iter(chunks), buffer_size=3))
    assert len(out) == 7
    for i, c in enumerate(out):
        np.testing.assert_array_equal(np.asarray(c["a"]),
                                      chunks[i]["a"])


def test_streaming_pads_non_multiple_chunks():
    # 1000-row chunks with batch_size=256 (not a divisor) must still fit
    import numpy as np
    from transmogrifai_tpu.models.sparse import (fit_sparse_lr,
                                                 fit_sparse_lr_streaming)

    rng = np.random.default_rng(0)
    n, K, D, B = 1000, 4, 3, 128
    idx = rng.integers(0, B, size=(n, K), dtype=np.int32)
    num = rng.normal(size=(n, D)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)

    def chunks():
        yield {"idx": idx, "num": num, "y": y, "w": np.ones(n, np.float32)}

    p_stream = fit_sparse_lr_streaming(chunks, B, D, epochs=1,
                                       batch_size=256)
    p_dense = fit_sparse_lr(idx, num, y, np.ones(n, np.float32), B,
                            epochs=1, batch_size=256)
    np.testing.assert_allclose(p_stream["table"], p_dense["table"],
                               rtol=1e-5, atol=1e-6)


def _xor_interaction_data(rng, n=6000, card=8, buckets=1 << 10):
    """Label = XOR of two fields' parities (+10% noise): ZERO marginal
    signal per hashed token, all signal in the field cross — the regime
    FM exists for and hashed LR cannot express."""
    c0 = rng.integers(0, card, n)
    c1 = rng.integers(0, card, n)
    y = ((c0 % 2) ^ (c1 % 2)).astype(np.float32)
    y = np.where(rng.random(n) < 0.9, y, 1 - y)
    idx = np.stack([hash_tokens([f"a|{v}" for v in c0], buckets, 42),
                    hash_tokens([f"b|{v}" for v in c1], buckets, 42)],
                   1).astype(np.int32)
    return idx, np.zeros((n, 1), np.float32), y


def test_sparse_fm_learns_interactions_lr_cannot(rng):
    from transmogrifai_tpu.evaluators.functional import auroc
    from transmogrifai_tpu.models.sparse import fit_sparse_fm
    import jax.numpy as jnp

    idx, X, y = _xor_interaction_data(rng)
    w = np.ones_like(y)
    B = 1 << 10
    plr = fit_sparse_lr(idx, X, y, w, B, lr=0.1, epochs=3, batch_size=512)
    a_lr = float(auroc(jnp.asarray(predict_sparse_lr(plr, idx, X)[:, 1]),
                       jnp.asarray(y), None))
    pfm = fit_sparse_fm(idx, X, y, w, B, k=8, lr=0.1, epochs=3,
                        batch_size=512)
    a_fm = float(auroc(jnp.asarray(predict_sparse_lr(pfm, idx, X)[:, 1]),
                       jnp.asarray(y), None))
    assert a_lr < 0.62, a_lr          # LR is ~chance on pure interaction
    assert a_fm > 0.80, a_fm          # FM captures the cross
    assert "emb" in pfm               # predict dispatched the FM path


def test_sparse_fm_streaming_matches_in_memory(rng):
    from transmogrifai_tpu.models.sparse import (fit_sparse_fm,
                                                 fit_sparse_fm_streaming)

    idx, nums, y = _ctr_data(rng, 2048)
    w = np.ones_like(y)
    full = fit_sparse_fm(idx, nums, y, w, 1 << 12, k=4, lr=0.1,
                         epochs=2, batch_size=256, seed=7)

    def chunks():
        for s in range(0, 2048, 512):
            sl = slice(s, s + 512)
            yield {"idx": idx[sl], "num": nums[sl], "y": y[sl], "w": w[sl]}

    stream = fit_sparse_fm_streaming(chunks, 1 << 12, nums.shape[1], k=4,
                                     lr=0.1, epochs=2, batch_size=256,
                                     seed=7)
    np.testing.assert_allclose(stream["table"], full["table"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(stream["emb"], full["emb"],
                               rtol=1e-5, atol=1e-6)


def test_sparse_selector_fm_wins_on_interaction_data(rng):
    """Three families compete; on cross-only signal the FM must win the
    sweep and the streamed refit must produce a working model."""
    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.models.sparse import SparseModelSelector

    idx, X, y = _xor_interaction_data(rng, n=3000)
    ds = Dataset({"y": y.astype(np.float64), "sx": idx, "nx": X},
                 {"y": ft.RealNN, "sx": ft.SparseIndices,
                  "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column() \
        .as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    sel = SparseModelSelector(
        num_buckets=1 << 10, n_folds=2, epochs=2, refit_epochs=3,
        batch_size=256, chunk_rows=1000, fm_dim=8,
        grid=[{"family": "adagrad", "lr": 0.1, "l2": 0.0},
              {"family": "ftrl", "alpha": 0.3, "l1": 0.0},
              {"family": "fm", "lr": 0.1, "l2": 0.0}],
    ).set_input(fy, fs, fn)
    model, _ = sel.fit_transform(ds)
    summ = model.summary
    fams = {r["family"] for r in summ["validationResults"]}
    assert fams == {"SparseLogisticRegression", "SparseFTRL",
                    "SparseFactorizationMachine"}
    assert summ["bestModel"]["family"] == "SparseFactorizationMachine"
    assert summ["trainEvaluation"]["AuROC"] > 0.8
    # fitted FM round-trips through stage JSON like the LR families
    import json
    from transmogrifai_tpu.stages import stage_from_json, stage_to_json
    loaded = stage_from_json(json.loads(json.dumps(
        stage_to_json(model), default=lambda o: o.tolist()
        if isinstance(o, np.ndarray) else o)))
    ds2 = loaded.transform(ds)
    col = ds2.column(loaded.output.name)
    assert {"prediction", "probability_1"} <= set(col[0])


def test_sparse_lr_sharded_matches_single_device(rng):
    """Minibatch rows sharded over the 8-device data mesh + replicated
    table: GSPMD's psum'd scatter-add gradient must reproduce the
    single-device fit (the treeAggregate-parity contract the dense DP
    paths already pin)."""
    from transmogrifai_tpu.models.sparse import fit_sparse_lr_sharded
    from transmogrifai_tpu.parallel.data_parallel import data_mesh

    idx, nums, y = _ctr_data(rng, 2000)
    w = np.ones_like(y)
    single = fit_sparse_lr(idx, nums, y, w, 1 << 12, lr=0.1, l2=1e-6,
                           epochs=2, batch_size=256)
    sharded = fit_sparse_lr_sharded(idx, nums, y, w, 1 << 12,
                                    mesh=data_mesh(), lr=0.1, l2=1e-6,
                                    epochs=2, batch_size=256)
    np.testing.assert_allclose(sharded["table"], single["table"],
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(sharded["dense"], single["dense"],
                               rtol=1e-4, atol=1e-6)


def test_sparse_selector_families_compete(rng):
    """Both families sweep in ONE selector fit; validationResults spans
    families and the summary names the winner (VERDICT r3 item 3)."""
    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.models.sparse import SparseModelSelector

    n = 2400
    idx, nums, y = _ctr_data(rng, n)
    ds = Dataset({"y": y.astype(np.float64), "sx": idx, "nx": nums},
                 {"y": ft.RealNN, "sx": ft.SparseIndices,
                  "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column() \
        .as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    sel = SparseModelSelector(
        num_buckets=1 << 12, n_folds=2, epochs=2, refit_epochs=2,
        batch_size=256, chunk_rows=800,   # sweep streams 3 chunks
        grid=[{"family": "adagrad", "lr": 0.1, "l2": 0.0},
              {"family": "ftrl", "alpha": 0.3, "l1": 0.0}],
    ).set_input(fy, fs, fn)
    model, out = sel.fit_transform(ds)
    summ = model.summary
    fams = {r["family"] for r in summ["validationResults"]}
    assert fams == {"SparseLogisticRegression", "SparseFTRL"}
    assert all(np.isfinite(r["logloss"]) for r in summ["validationResults"])
    assert summ["bestModel"]["family"] in fams
    # a genuine competition: both families beat the base-rate logloss
    pr = float(np.clip(y.mean(), 1e-6, 1 - 1e-6))
    base_ll = float(-(pr * np.log(pr) + (1 - pr) * np.log(1 - pr)))
    assert all(r["logloss"] < base_ll for r in summ["validationResults"]), \
        (summ["validationResults"], base_ll)
    # FTRL winner must refit + predict through the same param shape
    col = out.column(model.output.name)
    assert {"prediction", "probability_1"} <= set(col[0])


def test_sparse_selector_ftrl_can_win(rng):
    """When the adagrad candidate is crippled (lr ~ 0), FTRL must win
    and the streamed refit must produce a working model."""
    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.models.sparse import SparseModelSelector

    idx, nums, y = _ctr_data(rng, 1600)
    ds = Dataset({"y": y.astype(np.float64), "sx": idx, "nx": nums},
                 {"y": ft.RealNN, "sx": ft.SparseIndices,
                  "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column() \
        .as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    sel = SparseModelSelector(
        num_buckets=1 << 12, n_folds=2, epochs=1, refit_epochs=2,
        batch_size=256, chunk_rows=600,
        grid=[{"family": "adagrad", "lr": 1e-6, "l2": 0.0},
              {"family": "ftrl", "alpha": 0.3, "l1": 0.0}],
    ).set_input(fy, fs, fn)
    model, _ = sel.fit_transform(ds)
    assert model.summary["bestModel"]["family"] == "SparseFTRL"
    assert model.summary["trainEvaluation"]["AuROC"] > 0.7


def test_sparse_softmax_multiclass(rng):
    """Multiclass softmax over hashed features: learnability on a
    3-class synthetic, streaming/in-memory parity, stage persistence,
    row-path parity, and the portable no-jax roundtrip."""
    import json
    from transmogrifai_tpu.models.sparse import (
        SparseSoftmaxRegression, fit_sparse_softmax,
        fit_sparse_softmax_streaming, predict_sparse_softmax)
    from transmogrifai_tpu.stages import stage_from_json, stage_to_json

    n, B = 3072, 1 << 10     # chunk/batch-aligned: 4 x 768, 768 = 3 x 256
    rng2 = np.random.default_rng(23)
    c0 = rng2.integers(0, 9, n)
    y = (c0 % 3).astype(np.float32)          # class = field value mod 3
    flip = rng2.random(n) < 0.1
    y = np.where(flip, rng2.integers(0, 3, n), y).astype(np.float32)
    idx = np.stack([hash_tokens([f"a|{v}" for v in c0], B, 42),
                    hash_tokens([f"b|{v}" for v in
                                 rng2.integers(0, 40, n)], B, 42)],
                   1).astype(np.int32)
    X = rng2.normal(size=(n, 2)).astype(np.float32)
    w = np.ones(n, np.float32)

    params = fit_sparse_softmax(idx, X, y, w, B, 3, lr=0.2, epochs=3,
                                batch_size=256)
    probs = predict_sparse_softmax(params, idx, X)
    assert probs.shape == (n, 3)
    np.testing.assert_allclose(probs.sum(1), 1.0, rtol=1e-4)
    acc = float((probs.argmax(1) == y).mean())
    assert acc > 0.85, acc

    def chunks():
        for s in range(0, n, 768):
            sl = slice(s, s + 768)
            yield {"idx": idx[sl], "num": X[sl], "y": y[sl], "w": w[sl]}

    stream = fit_sparse_softmax_streaming(chunks, B, 2, 3, lr=0.2,
                                          epochs=3, batch_size=256)
    np.testing.assert_allclose(stream["table"], params["table"],
                               rtol=1e-5, atol=1e-6)

    # stage surface: fit -> Prediction dicts, persistence, row parity
    ds = Dataset({"y": y.astype(np.float64), "sx": idx, "nx": X},
                 {"y": ft.RealNN, "sx": ft.SparseIndices,
                  "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column() \
        .as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    est = SparseSoftmaxRegression(num_buckets=B, lr=0.2, epochs=2,
                                  batch_size=256).set_input(fy, fs, fn)
    model, out = est.fit_transform(ds)
    col = out.column(model.output.name)
    assert {"prediction", "probability_0", "probability_2"} <= set(col[0])
    loaded = stage_from_json(json.loads(json.dumps(
        stage_to_json(model), default=lambda o: o.tolist()
        if isinstance(o, np.ndarray) else o)))
    col2 = loaded.transform(ds).column(loaded.output.name)
    assert col[5]["probability_1"] == pytest.approx(
        col2[5]["probability_1"], abs=1e-6)
    row = model.transform_value(ft.RealNN(0.0),
                                ft.SparseIndices(tuple(idx[5])),
                                ft.OPVector(tuple(map(float, X[5]))))
    assert row.value["prediction"] == col[5]["prediction"]

    # portable no-jax roundtrip through the workflow export
    from transmogrifai_tpu.workflow import Workflow
    pred = SparseSoftmaxRegression(num_buckets=B, lr=0.2, epochs=2,
                                   batch_size=256
                                   ).set_input(fy, fs, fn).output
    wf_model = Workflow([pred]).train(ds)
    import importlib.util, os, tempfile
    with tempfile.TemporaryDirectory() as td:
        scorer = wf_model.compile_scoring()
        want = scorer.score_arrays(ds)
        wf_model.export_portable(td)
        spec = importlib.util.spec_from_file_location(
            "rt_softmax", os.path.join(td, "portable_runtime.py"))
        rt = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(rt)
        got = rt.load(td).score_columns({"sx": idx, "nx": X})
        for k in want:
            np.testing.assert_allclose(got[k], want[k], rtol=2e-4,
                                       atol=2e-5)


def test_softmax_streaming_validates_class_ids():
    """The streamed fit applies the same per-chunk class-id guard as the
    in-memory fit — bad ids fail fast, not as silent clamping."""
    from transmogrifai_tpu.models.sparse import fit_sparse_softmax_streaming

    def chunks():
        yield {"idx": np.zeros((256, 2), np.int32),
               "num": np.zeros((256, 1), np.float32),
               "y": np.full(256, 3.0, np.float32),     # out of range
               "w": np.ones(256, np.float32)}

    with pytest.raises(ValueError, match="label ids"):
        fit_sparse_softmax_streaming(chunks, 64, 1, 3, batch_size=256)


def test_softmax_sweep_and_selector_guard(rng):
    """family='softmax' sweeps multiclass CE over the same chunked grid
    machinery; the binary selector rejects softmax grid entries with a
    clear error instead of mis-fitting."""
    from transmogrifai_tpu.models.sparse import (SparseModelSelector,
                                                 validate_sparse_grid)

    n, B = 2400, 1 << 10
    rng2 = np.random.default_rng(29)
    c0 = rng2.integers(0, 9, n)
    y = (c0 % 3).astype(np.float32)
    idx = np.stack([hash_tokens([f"a|{v}" for v in c0], B, 42),
                    hash_tokens([f"b|{v}" for v in
                                 rng2.integers(0, 30, n)], B, 42)],
                   1).astype(np.int32)
    X = np.zeros((n, 1), np.float32)
    res = validate_sparse_grid(
        idx, X, y,
        [{"family": "softmax", "lr": 0.2, "l2": 0.0},
         {"family": "softmax", "lr": 1e-5, "l2": 0.0}],
        n_buckets=B, n_folds=2, epochs=2, batch_size=256, n_classes=3)
    assert res["best_hyper"]["lr"] == 0.2     # near-zero lr barely learns
    assert all(np.isfinite(res["logloss"]))
    # n_classes is required for softmax sweeps
    with pytest.raises(ValueError, match="n_classes"):
        validate_sparse_grid(idx, X, y,
                             [{"family": "softmax", "lr": 0.1}],
                             n_buckets=B, n_folds=2, batch_size=256)
    # the binary selector refuses softmax entries
    ds = Dataset({"y": y.astype(np.float64), "sx": idx, "nx": X},
                 {"y": ft.RealNN, "sx": ft.SparseIndices,
                  "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column() \
        .as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    sel = SparseModelSelector(
        num_buckets=B, grid=[{"family": "softmax", "lr": 0.1}]
    ).set_input(fy, fs, fn)
    with pytest.raises(ValueError, match="binary CTR front door"):
        sel.fit(ds)


def test_sparse_selector_balancer_reweights(rng):
    """splitter={"type": "balancer"} mirrors the dense selector: rare
    positives get upweighted (weights, never row counts), the summary
    records the balancer, and recall on the rare class improves over
    the unbalanced fit."""
    from transmogrifai_tpu import Dataset, FeatureBuilder
    from transmogrifai_tpu.models.sparse import SparseModelSelector

    n = 4000
    rng2 = np.random.default_rng(17)
    c0 = rng2.integers(0, 12, n)
    base = np.where(c0 % 3 == 0, -2.0, -5.0)      # ~5% positives overall
    y = (rng2.random(n) < 1 / (1 + np.exp(-base))).astype(np.float32)
    idx = np.stack([hash_tokens([f"a|{v}" for v in c0], 1 << 10, 42),
                    hash_tokens([f"b|{v}" for v in rng2.integers(0, 9, n)],
                                1 << 10, 42)], 1).astype(np.int32)
    X = np.zeros((n, 1), np.float32)
    ds = Dataset({"y": y.astype(np.float64), "sx": idx, "nx": X},
                 {"y": ft.RealNN, "sx": ft.SparseIndices,
                  "nx": ft.OPVector})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.SparseIndices, "sx").from_column() \
        .as_predictor()
    fn = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()

    def fit(splitter):
        sel = SparseModelSelector(
            num_buckets=1 << 10, n_folds=2, epochs=1, refit_epochs=2,
            batch_size=256, grid=[{"family": "adagrad", "lr": 0.1,
                                   "l2": 0.0}],
            splitter=splitter).set_input(fy, fs, fn)
        model, out = sel.fit_transform(ds)
        col = out.column(model.output.name)
        pred = np.asarray([r["prediction"] for r in col])
        pos = y > 0.5
        return model, float((pred[pos] > 0.5).mean())

    plain_model, plain_recall = fit(None)
    bal_model, bal_recall = fit({"type": "balancer",
                                 "sample_fraction": 0.5})
    assert bal_model.summary["splitterSummary"]["name"] == "DataBalancer"
    assert plain_model.summary["splitterSummary"]["name"] == "DataSplitter"
    assert bal_recall > plain_recall   # upweighted rare class found


def test_sparse_record_insights_loco(rng):
    """Per-record leave-one-FIELD-out on the hashed path: the signal
    field must dominate per-record deltas, the null-bucket
    counterfactual must match the vectorizer's missing-value semantics,
    and the stage must persist (RecordInsightsLOCO parity for sparse)."""
    import json as _json
    from transmogrifai_tpu.insights import SparseRecordInsightsLOCO
    from transmogrifai_tpu.models.sparse import SparseLogisticRegression
    from transmogrifai_tpu.ops.sparse import SparseHashingVectorizer

    n = 1500
    rng2 = np.random.default_rng(3)
    strong = rng2.integers(0, 6, n)          # drives the label
    weak = rng2.integers(0, 50, n)           # noise field
    nums = rng2.normal(size=(n, 2)).astype(np.float64)
    y = (rng2.random(n) < 1 / (1 + np.exp(
        -(np.where(strong % 2 == 0, 2.0, -2.0))))).astype(np.float64)
    ds = Dataset({"y": y, "s": np.array([f"v{v}" for v in strong], object),
                  "w": np.array([f"u{v}" for v in weak], object),
                  "n0": nums[:, 0], "n1": nums[:, 1]},
                 {"y": ft.RealNN, "s": ft.PickList, "w": ft.PickList,
                  "n0": ft.Real, "n1": ft.Real})
    fy = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fs = FeatureBuilder.of(ft.PickList, "s").from_column().as_predictor()
    fw = FeatureBuilder.of(ft.PickList, "w").from_column().as_predictor()
    f0 = FeatureBuilder.of(ft.Real, "n0").from_column().as_predictor()
    f1 = FeatureBuilder.of(ft.Real, "n1").from_column().as_predictor()
    vec = SparseHashingVectorizer(num_buckets=1 << 12).set_input(fs, fw)
    ds2 = vec.transform(ds)
    ds2 = Dataset(dict({k: ds2.column(k) for k in ds2.column_names},
                       nx=nums.astype(np.float32)),
                  dict(ds2.schema, nx=ft.OPVector))
    fy2 = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    fsx = FeatureBuilder.of(ft.SparseIndices, vec.output.name) \
        .from_column().as_predictor()
    fnx = FeatureBuilder.of(ft.OPVector, "nx").from_column().as_predictor()
    est = SparseLogisticRegression(num_buckets=1 << 12, lr=0.1, epochs=3,
                                   batch_size=256).set_input(fy2, fsx, fnx)
    model, _ = est.fit_transform(ds2)

    loco = SparseRecordInsightsLOCO.from_vectorizer(
        model, vec, dense_names=["n0", "n1"], top_k=4
    ).set_input(fsx, fnx)
    out = loco.transform(ds2)
    col = out.column(loco.output.name)
    # the signal field 's' must be the top contributor for most records
    tops = 0
    for i in range(0, n, 7):
        rec = col[i]
        first_key = next(iter(rec))
        deltas = {k: abs(_json.loads(v)[1]) for k, v in rec.items()}
        if max(deltas, key=deltas.get) == "s":
            tops += 1
        assert set(rec) <= {"s", "w", "n0", "n1"}
        assert first_key == max(deltas, key=deltas.get)
    assert tops / len(range(0, n, 7)) > 0.8
    # row path parity
    row = loco.transform_value(
        ft.SparseIndices(tuple(ds2.column(vec.output.name)[3])),
        ft.OPVector(tuple(map(float, nums[3]))))
    assert set(row.value) <= {"s", "w", "n0", "n1"}
    # persistence round-trip
    import json
    from transmogrifai_tpu.stages import stage_from_json, stage_to_json
    loaded = stage_from_json(json.loads(json.dumps(
        stage_to_json(loco), default=lambda o: o.tolist()
        if isinstance(o, np.ndarray) else o)))
    col2 = loaded.transform(ds2).column(loaded.output.name)
    assert col2[3] == col[3]


# ---------------------------------------------------------------------------
# Front-door flow: transmogrify_sparse -> SparseModelSelector -> runner
# ---------------------------------------------------------------------------

def _front_door_records(n, seed=0):
    rng = np.random.default_rng(seed)
    dev = rng.choice(["ios", "android", "web"], n, p=[.3, .5, .2])
    camp = rng.integers(0, 500, n)
    nums = rng.normal(size=(n, 2))
    logit = (np.where(dev == "ios", 2.2, -1.1)
             + np.where(camp % 3 == 0, 1.6, -0.9) + 1.0 * nums[:, 0])
    y = (rng.random(n) < 1 / (1 + np.exp(-logit))).astype(float)
    return [{"device": str(dev[i]), "campaign": f"c{camp[i]}",
             "num0": float(nums[i, 0]), "num1": float(nums[i, 1]),
             "click": float(y[i])} for i in range(n)]


def _front_door_workflow(buckets=1 << 12):
    from transmogrifai_tpu.models.sparse import SparseModelSelector
    from transmogrifai_tpu.ops.transmogrifier import transmogrify_sparse
    from transmogrifai_tpu.workflow import Workflow

    click = FeatureBuilder.of(ft.RealNN, "click").from_column().as_response()
    cats = [FeatureBuilder.of(ft.PickList, c).from_column().as_predictor()
            for c in ("device", "campaign")]
    nums = [FeatureBuilder.of(ft.Real, f"num{j}").from_column().as_predictor()
            for j in range(2)]
    hashed, dense = transmogrify_sparse(cats + nums, num_buckets=buckets)
    assert issubclass(hashed.wtype, ft.SparseIndices)
    assert issubclass(dense.wtype, ft.OPVector)
    pred = SparseModelSelector(
        num_buckets=buckets, n_folds=2, epochs=1, refit_epochs=2,
        batch_size=512, chunk_rows=700,   # forces multi-chunk streaming
        grid=[{"lr": 0.05, "l2": 0.0}, {"lr": 0.1, "l2": 0.0}],
    ).set_input(click, hashed, dense).output
    return Workflow([pred])


def test_transmogrify_sparse_routing_and_errors():
    from transmogrifai_tpu.ops.transmogrifier import transmogrify_sparse

    num = FeatureBuilder.of(ft.Real, "x").from_column().as_predictor()
    cat = FeatureBuilder.of(ft.PickList, "c").from_column().as_predictor()
    resp = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    with pytest.raises(ValueError, match="no Text-typed"):
        transmogrify_sparse([num])
    with pytest.raises(ValueError, match="dense numeric block"):
        transmogrify_sparse([cat])
    with pytest.raises(ValueError, match="response"):
        transmogrify_sparse([cat, num, resp])
    s, d = transmogrify_sparse([cat, num], num_buckets=256)
    assert issubclass(s.wtype, ft.SparseIndices)
    assert issubclass(d.wtype, ft.OPVector)


def test_sparse_selector_front_door_runner_e2e(tmp_path):
    """WorkflowRunner TRAIN/SCORE/EVALUATE over the sparse front door:
    summary parity shape, streaming multi-chunk refit, persistence."""
    from transmogrifai_tpu.evaluators import Evaluators
    from transmogrifai_tpu.readers import DataReaders
    from transmogrifai_tpu.runner import OpParams, RunType, WorkflowRunner
    from transmogrifai_tpu.workflow import WorkflowModel

    reader = DataReaders.simple(_front_door_records(3000))
    wf = _front_door_workflow()
    runner = WorkflowRunner(wf, train_reader=reader, score_reader=reader,
                            evaluator=Evaluators.binary_classification())
    params = OpParams(model_location=str(tmp_path / "model"),
                      metrics_location=str(tmp_path / "metrics"),
                      response="click")
    train_res = runner.run(RunType.TRAIN, params)
    assert train_res["bestModel"]["family"] == "SparseLogisticRegression"
    assert train_res["bestModel"]["hyper"]["lr"] in (0.05, 0.1)
    ev = runner.run(RunType.EVALUATE, params)
    assert ev["metrics"]["AuROC"] > 0.8

    m = WorkflowModel.load(str(tmp_path / "model"))
    sel = m.selected_model()
    assert sel is not None, "selected_model() must find SparseSelectedModel"
    summ = sel.summary
    # per-field contributions: one per index column, the two signal
    # fields (device, campaign) must outweigh the numerics-only zeros
    fc = summ["fieldContributions"]
    assert len(fc) == 2 and all(c > 0 for c in fc)
    # global ModelInsights works for the sparse selector too
    from transmogrifai_tpu.insights import model_insights
    mi = model_insights(m)
    assert mi["selectedModelInfo"]["bestModel"]["family"] \
        == summ["bestModel"]["family"]
    assert mi["trainingParams"]["modelFamily"] == summ["bestModel"]["family"]
    assert {"validationType", "splitterSummary", "validationResults",
            "bestModel", "trainEvaluation", "holdoutEvaluation",
            "dataCounts"} <= set(summ)
    assert len(summ["validationResults"]) == 2
    assert summ["holdoutEvaluation"]["AuROC"] > 0.75
    # loaded model scores
    ds = m.score(reader.generate_dataset(m.raw_features))
    col = ds.column(m.result_features[0].name)
    assert {"prediction", "probability_1"} <= set(col[0])


def test_hash_collision_stats_monotone():
    from transmogrifai_tpu.ops.sparse import hash_collision_stats

    toks = [f"f|{i}" for i in range(20_000)]
    stats = hash_collision_stats(toks, widths=(1 << 12, 1 << 16, 1 << 20))
    fracs = [stats[w]["colliding_token_fraction"]
             for w in (1 << 12, 1 << 16, 1 << 20)]
    assert all(0.0 <= f <= 1.0 for f in fracs)
    assert fracs[0] > fracs[1] > fracs[2]     # wider space, fewer collisions
    assert stats[1 << 12]["distinct_tokens"] == 20_000.0
    # narrow space MUST collide heavily; huge space barely
    assert fracs[0] > 0.5
    assert fracs[2] < 0.02


def test_fold_hash_deterministic_balanced_and_offset_stable():
    """The splitmix64 fold assignment must be (a) deterministic, (b)
    roughly balanced, and (c) a pure function of the GLOBAL row index —
    so any chunking of the same stream yields identical folds."""
    from transmogrifai_tpu.models.sparse import _fold_ids

    n, F = 50_000, 3
    a = _fold_ids(0, n, F, seed=42)
    b = _fold_ids(0, n, F, seed=42)
    np.testing.assert_array_equal(a, b)
    counts = np.bincount(a, minlength=F) / n
    assert np.all(np.abs(counts - 1 / F) < 0.01), counts
    # chunked == contiguous (offset addressing)
    chunked = np.concatenate([_fold_ids(s, 1000, F, seed=42)
                              for s in range(0, n, 1000)])
    np.testing.assert_array_equal(chunked, a)
    # a different seed produces a different assignment
    assert not np.array_equal(_fold_ids(0, n, F, seed=7), a)


def test_sparse_fm_and_softmax_sharded_match_single_device():
    """The generalized mesh-DP fit reproduces the single-chip FM and
    softmax fits on the 8-device data mesh (same treeAggregate-parity
    contract as the LR family)."""
    from transmogrifai_tpu.models.sparse import (
        fit_sparse_fm, fit_sparse_fm_sharded, fit_sparse_softmax,
        fit_sparse_softmax_sharded)
    from transmogrifai_tpu.parallel.data_parallel import data_mesh

    mesh = data_mesh()
    n, K, D, B = 1024, 4, 3, 1 << 10
    rng2 = np.random.default_rng(31)
    idx = rng2.integers(0, B, size=(n, K)).astype(np.int32)
    X = rng2.normal(size=(n, D)).astype(np.float32)
    w = np.ones(n, np.float32)

    yb = (rng2.random(n) < 0.5).astype(np.float32)
    a = fit_sparse_fm(idx, X, yb, w, B, k=4, lr=0.1, epochs=1,
                      batch_size=256, seed=3)
    b = fit_sparse_fm_sharded(idx, X, yb, w, B, mesh=mesh, k=4, lr=0.1,
                              epochs=1, batch_size=256, seed=3)
    np.testing.assert_allclose(b["emb"], a["emb"], rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(b["table"], a["table"], rtol=1e-4,
                               atol=1e-6)

    ym = rng2.integers(0, 3, n).astype(np.float32)
    c = fit_sparse_softmax(idx, X, ym, w, B, 3, lr=0.2, epochs=1,
                           batch_size=256)
    d = fit_sparse_softmax_sharded(idx, X, ym, w, B, 3, mesh=mesh,
                                   lr=0.2, epochs=1, batch_size=256)
    np.testing.assert_allclose(d["table"], c["table"], rtol=1e-4,
                               atol=1e-6)
    with pytest.raises(ValueError, match="label ids"):
        fit_sparse_softmax_sharded(idx, X, ym + 5, w, B, 3, mesh=mesh)


def test_uniform_chunks_pads_tail_to_first_shape():
    """Ragged tail chunks pad up to the first chunk's row count (one
    compiled program per stream); padding rows carry w=0 so fits are
    unchanged."""
    import numpy as np
    from transmogrifai_tpu.models import sparse as S

    def chunks(sizes):
        for s in sizes:
            yield {"idx": np.ones((s, 3), np.int32),
                   "num": np.ones((s, 2), np.float32),
                   "y": np.ones(s, np.float32),
                   "w": np.ones(s, np.float32)}

    out = list(S._uniform_chunks(chunks([100, 100, 37])))
    assert [len(c["y"]) for c in out] == [100, 100, 100]
    tail = out[-1]
    assert tail["w"][:37].all() and not tail["w"][37:].any()
    assert tail["idx"].shape == (100, 3) and tail["num"].shape == (100, 2)
    # a LARGER chunk keeps its size
    out2 = list(S._uniform_chunks(chunks([50, 80])))
    assert [len(c["y"]) for c in out2] == [50, 80]

    # e2e: a ragged-tail stream fits identically to the same rows in
    # equal chunks (w=0 padding must be inert through the epoch step)
    rng = np.random.default_rng(7)
    n, K, d = 192, 4, 3
    idx = rng.integers(0, 64, (n, K)).astype(np.int32)
    num = rng.normal(size=(n, d)).astype(np.float32)
    y = (rng.random(n) > 0.5).astype(np.float32)
    w = np.ones(n, np.float32)

    def factory(sizes):
        def make():
            off = 0
            for s in sizes:
                sl = slice(off, off + s)
                off += s
                yield {"idx": idx[sl], "num": num[sl], "y": y[sl],
                       "w": w[sl]}
        return make

    p1 = S.fit_sparse_lr_streaming(factory([64, 64, 64]), 64, d,
                                   epochs=2, batch_size=32)
    p2 = S.fit_sparse_lr_streaming(factory([64, 64, 40, 24]), 64, d,
                                   epochs=2, batch_size=32)
    # different chunking = different update order (order-dependent
    # Adagrad), so just require both to be finite and close in norm;
    # the INERTNESS of padding is what this pins: ragged vs padded of
    # the SAME chunking must be bit-identical
    p3 = S.fit_sparse_lr_streaming(factory([64, 64, 40, 24]), 64, d,
                                   epochs=2, batch_size=32)
    np.testing.assert_array_equal(p2["table"], p3["table"])
    assert np.isfinite(p1["table"]).all() and np.isfinite(p2["table"]).all()


def test_selector_refit_checkpoint_resume(tmp_path):
    """Front-door checkpointing: a SparseModelSelector fit killed during
    the winner's refit resumes on re-fit and matches the uninterrupted
    model's holdout AUROC exactly (same seed, same chunks)."""
    import numpy as np

    from transmogrifai_tpu import FeatureBuilder
    from transmogrifai_tpu.dataset import Dataset
    from transmogrifai_tpu.features import types as ft
    from transmogrifai_tpu.io import stream as iostream
    from transmogrifai_tpu.models.sparse import SparseModelSelector

    rng = np.random.default_rng(4)
    n, K, B = 4096, 3, 1 << 10
    idx = rng.integers(0, B, size=(n, K), dtype=np.int32)
    Xn = rng.normal(size=(n, 2)).astype(np.float32)
    y = (rng.random(n) < 0.4).astype(np.float64)
    ds = Dataset({"y": y, "sidx": idx, "dense": Xn},
                 {"y": ft.RealNN, "sidx": ft.SparseIndices,
                  "dense": ft.OPVector})
    lbl = FeatureBuilder.of(ft.RealNN, "y").from_column().as_response()
    sf = FeatureBuilder.of(ft.SparseIndices, "sidx").from_column() \
        .as_predictor()
    dn = FeatureBuilder.of(ft.OPVector, "dense").from_column() \
        .as_predictor()

    def make_sel(ck):
        return SparseModelSelector(
            num_buckets=B, n_folds=2, epochs=1, refit_epochs=2,
            batch_size=512, chunk_rows=1024,
            grid=[{"family": "adagrad", "lr": 0.05, "l2": 0.0}],
            checkpoint_dir=ck,
        ).set_input(lbl, sf, dn)

    want = make_sel(None).fit(ds)

    # kill the refit mid-stream by poisoning the 5th step of the SECOND
    # fit_streaming call (the first call is the validation sweep)
    ck = str(tmp_path / "sel_ck")
    orig = iostream.fit_streaming

    def wrapped(step_fn, state, chunks, **kw):
        # the refit is the fit_streaming call that carries checkpoint_dir
        # (the validation sweep runs its own folded loop)
        if kw.get("checkpoint_dir"):
            n_steps = {"n": 0}

            def dying(s, c):
                n_steps["n"] += 1
                if n_steps["n"] > 5:
                    raise KeyboardInterrupt("kill refit")
                return step_fn(s, c)
            kw = dict(kw, checkpoint_every=2)
            return orig(dying, state, chunks, **kw)
        return orig(step_fn, state, chunks, **kw)

    iostream.fit_streaming = wrapped
    try:
        with pytest.raises(KeyboardInterrupt):
            make_sel(ck).fit(ds)
    finally:
        iostream.fit_streaming = orig
    import os as _os
    assert _os.path.exists(
        _os.path.join(ck, "refit_adagrad", "stream_fit.ckpt.npz"))

    got = make_sel(ck).fit(ds)
    assert got.summary["holdoutEvaluation"]["AuROC"] == \
        want.summary["holdoutEvaluation"]["AuROC"]
    assert not _os.path.exists(
        _os.path.join(ck, "refit_adagrad", "stream_fit.ckpt.npz"))
