"""Vectorizer + Transmogrifier tests (reference analog:
core/src/test/.../stages/impl/feature/*VectorizerTest.scala,
TransmogrifierTest.scala)."""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.features.manifest import NULL_INDICATOR, OTHER_INDICATOR
from transmogrifai_tpu import ops
from transmogrifai_tpu.stages import stage_from_json, stage_to_json


def feat(name, t):
    return FeatureBuilder.of(t, name).from_column().as_predictor()


def test_real_vectorizer_mean_impute_and_null_track():
    f = feat("x", ft.Real)
    ds = Dataset.from_dict({"x": [1.0, None, 3.0]}, {"x": ft.Real})
    model, out = ops.RealVectorizer(fill_with="mean").set_input(f).fit_transform(ds)
    arr = out.column(model.output.name)
    np.testing.assert_allclose(arr, [[1, 0], [2, 1], [3, 0]])
    man = out.manifest(model.output.name)
    assert man.column_names() == ["x_value", f"x_{NULL_INDICATOR}"]
    # row path agrees
    assert model.transform_value(ft.Real(None)).value == (2.0, 1.0)


def test_binary_vectorizer():
    f = feat("b", ft.Binary)
    ds = Dataset.from_dict({"b": [True, None, False]}, {"b": ft.Binary})
    t = ops.BinaryVectorizer().set_input(f)
    arr = t.transform(ds).column(t.output.name)
    np.testing.assert_allclose(arr, [[1, 0], [0, 1], [0, 0]])


def test_onehot_topk_other_null():
    f = feat("c", ft.PickList)
    vals = ["a"] * 5 + ["b"] * 3 + ["c"] * 1 + [None]
    ds = Dataset.from_dict({"c": vals}, {"c": ft.PickList})
    model, out = ops.OneHotVectorizer(top_k=2).set_input(f).fit_transform(ds)
    man = out.manifest(model.output.name)
    assert man.column_names() == [
        "c_a", "c_b", f"c_{OTHER_INDICATOR}", f"c_{NULL_INDICATOR}"]
    arr = out.column(model.output.name)
    assert arr[0].tolist() == [1, 0, 0, 0]       # "a"
    assert arr[8].tolist() == [0, 0, 1, 0]       # "c" -> OTHER
    assert arr[9].tolist() == [0, 0, 0, 1]       # None -> null track
    # persistence round trip preserves labels
    loaded = stage_from_json(stage_to_json(model))
    assert loaded.params["labels"] == ["a", "b"]


def test_multipicklist_vectorizer():
    f = feat("m", ft.MultiPickList)
    ds = Dataset.from_dict(
        {"m": [{"x", "y"}, {"x"}, set()]}, {"m": ft.MultiPickList})
    model, out = ops.MultiPickListVectorizer(top_k=2).set_input(f).fit_transform(ds)
    arr = out.column(model.output.name)
    man = out.manifest(model.output.name)
    names = man.column_names()
    ix, iy = names.index("m_x"), names.index("m_y")
    assert arr[0][ix] == 1 and arr[0][iy] == 1
    assert arr[2][names.index(f"m_{NULL_INDICATOR}")] == 1


def test_text_hashing_deterministic():
    f = feat("t", ft.Text)
    ds = Dataset.from_dict({"t": ["hello world hello", None]}, {"t": ft.Text})
    t = ops.TextHashingVectorizer(num_bins=8).set_input(f)
    arr = t.transform(ds).column(t.output.name)
    assert arr[0].sum() == 3.0  # three tokens counted
    assert arr[1][8] == 1.0     # null track
    # same input hashes identically across stage instances (stable murmur3)
    t2 = ops.TextHashingVectorizer(num_bins=8).set_input(f)
    np.testing.assert_array_equal(arr, t2.transform(ds).column(t2.output.name))


def test_smart_text_switches_mode():
    f = feat("t", ft.Text)
    low = Dataset.from_dict({"t": ["a", "b", "a", None]}, {"t": ft.Text})
    m1 = ops.SmartTextVectorizer(max_cardinality=5).set_input(f).fit(low)
    assert m1.params["mode"] == "pivot"
    high_vals = [f"word{i} filler" for i in range(50)]
    high = Dataset.from_dict({"t": high_vals}, {"t": ft.Text})
    m2 = ops.SmartTextVectorizer(max_cardinality=5, num_bins=16).set_input(f).fit(high)
    assert m2.params["mode"] == "hash"
    assert m2.transform(high).column(m2.output.name).shape[1] == 17
    # smart model persists and reloads with same behavior
    loaded = stage_from_json(stage_to_json(m2))
    np.testing.assert_array_equal(
        loaded.transform(high).column(loaded.output.name),
        m2.transform(high).column(m2.output.name))


def test_date_unit_circle():
    f = feat("d", ft.Date)
    day_ms = 24 * 3600_000
    ds = Dataset.from_dict({"d": [0, day_ms // 4, None]}, {"d": ft.Date})
    t = ops.DateToUnitCircle(time_period="HourOfDay").set_input(f)
    arr = t.transform(ds).column(t.output.name)
    np.testing.assert_allclose(arr[0], [0.0, 1.0, 0.0], atol=1e-12)  # midnight
    np.testing.assert_allclose(arr[1], [1.0, 0.0, 0.0], atol=1e-9)   # 6am
    assert arr[2].tolist() == [0.0, 0.0, 1.0]


def test_geolocation_vectorizer():
    f = feat("g", ft.Geolocation)
    ds = Dataset.from_dict(
        {"g": [(0.0, 0.0, 1.0), None]}, {"g": ft.Geolocation})
    model, out = ops.GeolocationVectorizer().set_input(f).fit_transform(ds)
    arr = out.column(model.output.name)
    np.testing.assert_allclose(arr[0], [1, 0, 0, 0], atol=1e-12)
    np.testing.assert_allclose(arr[1], [1, 0, 0, 1], atol=1e-12)  # mean-fill + null


def test_real_map_vectorizer():
    f = feat("m", ft.RealMap)
    ds = Dataset.from_dict(
        {"m": [{"a": 1.0, "b": 10.0}, {"a": 3.0}, {}]}, {"m": ft.RealMap})
    model, out = ops.RealMapVectorizer().set_input(f).fit_transform(ds)
    man = out.manifest(model.output.name)
    arr = out.column(model.output.name)
    assert man.column_names() == [
        "m_a_value", f"m_a_{NULL_INDICATOR}", "m_b_value", f"m_b_{NULL_INDICATOR}"]
    np.testing.assert_allclose(arr[1], [3.0, 0.0, 10.0, 1.0])  # b mean-imputed
    np.testing.assert_allclose(arr[2], [2.0, 1.0, 10.0, 1.0])


def test_text_map_pivot():
    f = feat("m", ft.PickListMap)
    ds = Dataset.from_dict(
        {"m": [{"k": "x"}, {"k": "y"}, {"k": "x"}, {}]}, {"m": ft.PickListMap})
    model, out = ops.TextMapPivotVectorizer(top_k=1).set_input(f).fit_transform(ds)
    man = out.manifest(model.output.name)
    names = man.column_names()
    arr = out.column(model.output.name)
    assert arr[0][names.index("m_k_x")] == 1
    assert arr[1][names.index(f"m_k_{OTHER_INDICATOR}")] == 1
    assert arr[3][names.index(f"m_k_{NULL_INDICATOR}")] == 1


def test_transmogrify_end_to_end():
    schema = {"age": ft.Real, "sex": ft.PickList, "alive": ft.Binary,
              "desc": ft.Text}
    ds = Dataset.from_dict(
        {"age": [10.0, None, 30.0, 40.0],
         "sex": ["m", "f", "m", None],
         "alive": [True, False, None, True],
         "desc": ["quick brown fox", "lazy dog", None, "fox"]},
        schema)
    feats = [feat(n, t) for n, t in schema.items()]
    combined = ops.transmogrify(feats)
    assert combined.wtype is ft.OPVector

    # fit the DAG by hand (workflow engine comes later)
    stage_order = []

    def collect(f):
        for p in f.parents:
            collect(p)
        if f.origin_stage is not None and f.origin_stage not in stage_order \
                and not f.is_raw:
            stage_order.append(f.origin_stage)
    collect(combined)

    cur = ds
    for st in stage_order:
        if hasattr(st, "fit"):
            st = st.fit(cur)
        cur = st.transform(cur)
    arr = cur.column(combined.name)
    man = cur.manifest(combined.name)
    assert arr.shape[0] == 4
    assert arr.shape[1] == man.size
    parents = set(man.by_parent())
    assert parents == {"age", "sex", "alive", "desc"}
    # feature type check: response features are rejected
    resp = FeatureBuilder.RealNN("y").from_column().as_response()
    with pytest.raises(ValueError):
        ops.transmogrify([resp])


def test_feature_dsl_vectorize():
    f = feat("x", ft.Real)
    out = f.vectorize(track_nulls=False)
    assert out.wtype is ft.OPVector
    assert out.origin_stage.params["track_nulls"] is False
    with pytest.raises(TypeError):
        f.vectorize(bogus_param=1)


def test_transmogrify_textarea_routing_knob():
    """textarea='smart' restores the reference-exact TextArea dispatch
    (SmartTextVectorizer); the default stays LDA topics; bad values
    raise (docs/MIGRATION.md 'things that changed deliberately')."""
    from transmogrifai_tpu.ops.transmogrifier import default_vectorizer

    f = FeatureBuilder.of(ft.TextArea, "doc").from_column().as_predictor()
    default = default_vectorizer(f)
    assert type(default).__name__ == "OpLDA"
    smart = default_vectorizer(f, textarea="smart")
    assert type(smart).__name__ == "SmartTextVectorizer"
    with pytest.raises(ValueError, match="textarea"):
        default_vectorizer(f, textarea="nope")
    # DSL parity: the knob reaches the Feature-method form too
    g = FeatureBuilder.of(ft.Real, "x").from_column().as_predictor()
    fv = f.transmogrify(g, textarea="smart")
    kinds = {type(st).__name__
             for st in (p.origin_stage for p in fv.parents)}
    assert "SmartTextVectorizer" in kinds


# ---------------------------------------------------------------------------
# Vectorized encoder paths vs the seed per-row loops (bitwise parity)
# ---------------------------------------------------------------------------

def _pivot_col(rng, n=600):
    vals = []
    for _ in range(n):
        r = rng.random()
        vals.append(None if r < 0.08 else "" if r < 0.12
                    else f"c{int(rng.integers(0, 40))}")
    return np.array(vals, dtype=object)


def test_onehot_vectorized_bitwise_parity(rng):
    """np.searchsorted label lookup must reproduce the seed dict-loop
    output BITWISE, including null/OTHER tracks, unseen labels, empty
    strings, and empty label sets."""
    col = _pivot_col(rng)
    for labels in ([f"c{j}" for j in range(25)], []):
        for tn in (True, False):
            for ot in (True, False):
                m = ops.OneHotModel(labels=labels, track_nulls=tn,
                                    other_track=ot)
                assert np.array_equal(m._vectorize(col),
                                      m._vectorize_rows(col))
    # empty column
    m = ops.OneHotModel(labels=["a"])
    empty = np.array([], dtype=object)
    assert np.array_equal(m._vectorize(empty), m._vectorize_rows(empty))


def test_multipicklist_vectorized_bitwise_parity(rng):
    tags = [f"t{j}" for j in range(30)]
    col = np.array(
        [None if rng.random() < 0.1 else frozenset(
            str(t) for t in rng.choice(tags, rng.integers(0, 5),
                                       replace=False))
         for _ in range(500)], dtype=object)
    for labels in ([f"t{j}" for j in range(15)], []):
        for ot in (True, False):
            m = ops.MultiPickListModel(labels=labels, other_track=ot)
            assert np.array_equal(m._vectorize(col),
                                  m._vectorize_rows(col))


def test_vectorized_fit_matches_counter_order(rng, monkeypatch):
    """The np.unique fit path must pick the SAME labels as the seed
    Counter path — count-descending with ties broken by first
    occurrence — across min_support/top_k cuts on tie-heavy data."""
    col = np.array([None if rng.random() < 0.1
                    else f"c{int(rng.integers(0, 9))}"
                    for _ in range(400)], dtype=object)
    ds = Dataset({"c": col}, {"c": ft.PickList})
    for top_k, ms in ((5, 1), (4, 3), (30, 1)):
        est = ops.OneHotVectorizer(top_k=top_k, min_support=ms
                                   ).set_input(feat("c", ft.PickList))
        monkeypatch.setenv("TM_VECTORIZE", "0")
        seed = est.fit_fn(ds)
        monkeypatch.setenv("TM_VECTORIZE", "1")
        assert est.fit_fn(ds) == seed


def test_tm_vectorize_env_restores_seed_loops(rng, monkeypatch):
    """TM_VECTORIZE=0 routes through the seed loops end to end; outputs
    are identical either way."""
    col = _pivot_col(rng, n=120)
    ds = Dataset({"c": col}, {"c": ft.PickList})
    f = feat("c", ft.PickList)
    monkeypatch.setenv("TM_VECTORIZE", "0")
    m0, out0 = ops.OneHotVectorizer().set_input(f).fit_transform(ds)
    monkeypatch.setenv("TM_VECTORIZE", "1")
    m1, out1 = ops.OneHotVectorizer().set_input(f).fit_transform(ds)
    assert m0.params["labels"] == m1.params["labels"]
    assert np.array_equal(out0.column(m0.output.name),
                          out1.column(m1.output.name))
