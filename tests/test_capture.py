"""Capture-daemon logic (tpu_capture.py): section priority, state
round-trip, and log format — the parts that must not rot while the
daemon idles for hours waiting on the device tunnel."""
import importlib.util
import json
import os
import sys


def _load():
    spec = importlib.util.spec_from_file_location(
        "tpu_capture_under_test",
        os.path.join(os.path.dirname(__file__), "..", "tpu_capture.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_priority_covers_all_device_sections():
    """Every device bench section must be in the capture priority list
    (a new section added to bench.py without capture coverage would
    silently never measure)."""
    cap = _load()
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = bench
    spec.loader.exec_module(bench)
    missing = set(bench._DEVICE_SECTIONS) - set(cap.PRIORITY)
    assert not missing, f"device sections not in capture priority: {missing}"
    unknown = set(cap.PRIORITY) - set(bench._SECTIONS)
    assert not unknown, f"capture priority names unknown sections: {unknown}"


def test_next_section_order_and_retry():
    cap = _load()
    assert cap.next_section({}) == cap.PRIORITY[0]
    st = {cap.PRIORITY[0]: {"ok": True}}
    assert cap.next_section(st) == cap.PRIORITY[1]
    # a failed section does NOT starve unattempted ones behind it
    # (a deterministic timeout would otherwise eat every alive-window);
    # it is retried only once everything else has had an attempt
    st[cap.PRIORITY[1]] = {"ok": False}
    assert cap.next_section(st) == cap.PRIORITY[2]
    st.update({name: {"ok": True} for name in cap.PRIORITY[2:]})
    assert cap.next_section(st) == cap.PRIORITY[1]
    done = {name: {"ok": True} for name in cap.PRIORITY}
    assert cap.next_section(done) is None


def test_state_roundtrip(tmp_path, monkeypatch):
    cap = _load()
    monkeypatch.setattr(cap, "STATE", str(tmp_path / "state.json"))
    assert cap.load_state() == {}
    cap.save_state({"lr_grid": {"ok": True, "result": {"v": 1.5}}})
    st = cap.load_state()
    assert st["lr_grid"]["result"]["v"] == 1.5
    # corrupt state never crashes the daemon loop
    with open(cap.STATE, "w") as f:
        f.write("{not json")
    assert cap.load_state() == {}


def test_log_appends_utc_lines(tmp_path, monkeypatch):
    cap = _load()
    monkeypatch.setattr(cap, "LOG", str(tmp_path / "probe.log"))
    cap.log("probe alive=False test")
    cap.log("second")
    lines = open(cap.LOG).read().splitlines()
    assert len(lines) == 2
    assert lines[0].endswith("probe alive=False test")
    assert lines[0][:4].isdigit() and "T" in lines[0][:20]  # ISO stamp
