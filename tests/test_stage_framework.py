"""Stage framework + Feature DAG + Dataset tests
(reference analog: core/src/test/.../stages/base/*Test.scala)."""
import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.stages import (
    UnaryTransformer, UnaryEstimator, BinaryTransformer, SequenceTransformer,
    LambdaTransformer, materialize_raw, stage_to_json, stage_from_json,
)


class DoubleIt(UnaryTransformer):
    in_type = ft.Real
    out_type = ft.Real
    operation_name = "double"

    def transform_value(self, v: ft.Real):
        return ft.Real(None if v.value is None else v.value * 2)


class MeanImpute(UnaryEstimator):
    in_type = ft.Real
    out_type = ft.Real
    operation_name = "impute"

    class Model(UnaryTransformer):
        in_type = ft.Real
        out_type = ft.Real
        operation_name = "impute"

        def __init__(self, mean=0.0, uid=None, **kw):
            super().__init__(uid=uid, mean=mean, **kw)

        def transform_value(self, v):
            return ft.Real(self.params["mean"] if v.value is None else v.value)

    model_cls = Model

    def fit_fn(self, ds):
        col = ds.column(self.input_names[0])
        m = float(np.nanmean(col)) if not np.all(np.isnan(col)) else 0.0
        return {"mean": m}


@pytest.fixture
def age_feature():
    return FeatureBuilder.Real("age").from_column().as_predictor()


def make_ds():
    schema = {"age": ft.Real, "fare": ft.Real, "name": ft.Text}
    return Dataset.from_dict(
        {"age": [10.0, None, 30.0], "fare": [1.0, 2.0, None],
         "name": ["a", None, "c"]}, schema)


def test_feature_dag_wiring(age_feature):
    doubled = DoubleIt().set_input(age_feature).output
    assert doubled.wtype is ft.Real
    assert doubled.parents == (age_feature,)
    assert age_feature.is_raw and not doubled.is_raw
    assert [f.name for f in doubled.raw_features()] == ["age"]


def test_type_checking(age_feature):
    name = FeatureBuilder.Text("name").from_column().as_predictor()
    with pytest.raises(TypeError):
        DoubleIt().set_input(name)


def test_unary_transform(age_feature):
    ds = make_ds()
    stage = DoubleIt().set_input(age_feature)
    out = stage.transform(ds)
    assert out.to_pylist(stage.output.name) == [20.0, None, 60.0]


def test_estimator_fit_transform(age_feature):
    ds = make_ds()
    est = MeanImpute().set_input(age_feature)
    model, out = est.fit_transform(ds)
    assert model.params["mean"] == 20.0
    assert out.to_pylist(model.output.name) == [10.0, 20.0, 30.0]
    # model shares the estimator's output feature
    assert model.output.uid == est.output.uid


def test_row_fn_local_scoring(age_feature):
    est = MeanImpute().set_input(age_feature)
    model = est.fit(make_ds())
    fn = model.make_row_fn()
    assert fn({"age": None}) == 20.0
    assert fn({"age": 5.0}) == 5.0


def test_stage_json_roundtrip(age_feature):
    est = MeanImpute().set_input(age_feature)
    model = est.fit(make_ds())
    d = stage_to_json(model)
    loaded = stage_from_json(d)
    assert type(loaded) is MeanImpute.Model
    assert loaded.params["mean"] == 20.0
    assert loaded.output.name == model.output.name
    assert loaded.make_row_fn()({"age": None}) == 20.0


def test_sequence_and_lambda():
    f1 = FeatureBuilder.Real("a").from_column().as_predictor()
    f2 = FeatureBuilder.Real("b").from_column().as_predictor()

    class SumAll(SequenceTransformer):
        in_type = ft.Real
        out_type = ft.Real
        operation_name = "sum"

        def transform_value(self, *vs):
            return ft.Real(sum(v.value or 0.0 for v in vs))

    out = SumAll().set_input(f1, f2).output
    ds = Dataset.from_dict({"a": [1.0, 2.0], "b": [10.0, None]},
                           {"a": ft.Real, "b": ft.Real})
    res = out.origin_stage.transform(ds)
    assert res.to_pylist(out.name) == [11.0, 2.0]

    lam = LambdaTransformer(lambda v: ft.Real((v.value or 0) + 1), ft.Real)
    outf = lam.set_input(f1).output
    assert lam.transform(ds).to_pylist(outf.name) == [2.0, 3.0]


def test_materialize_raw_and_from_dataset():
    records = [{"age": 1.0, "name": "x"}, {"age": None, "name": None}]
    age = FeatureBuilder.Real("age").from_column().as_predictor()
    name = FeatureBuilder.Text("name").from_column().as_predictor()
    ds = materialize_raw(records, [age, name])
    assert ds.n_rows == 2
    assert ds.to_pylist("age") == [1.0, None]

    full = make_ds()
    resp, preds = FeatureBuilder.from_dataset(full, response="fare")
    assert resp.wtype is ft.RealNN and resp.is_response
    assert {p.name for p in preds} == {"age", "name"}


def test_dataset_vector_columns():
    from transmogrifai_tpu.features.manifest import ColumnManifest, ColumnMeta
    arr = np.array([[1, 2], [3, 4]], dtype=np.float32)
    man = ColumnManifest([ColumnMeta("a", "Real"), ColumnMeta("b", "Real")])
    ds = Dataset({"v": arr}, {"v": ft.OPVector}, {"v": man})
    assert ds.manifest("v").size == 2
    assert ds.raw_value("v", 0) == (1.0, 2.0)
    taken = ds.take(np.array([1]))
    assert taken.manifest("v") is man


def test_nested_model_class_names_do_not_collide(age_feature):
    """Persisted className is module-qualified so two nested `Model` classes
    round-trip to the right class (regression: bare-name registry collision)."""
    class OtherEst(UnaryEstimator):
        in_type = ft.Real
        out_type = ft.Real

        class Model(UnaryTransformer):
            in_type = ft.Real
            out_type = ft.Real

            def __init__(self, mean=0.0, uid=None, **kw):
                super().__init__(uid=uid, mean=mean, **kw)

            def transform_value(self, v):
                return ft.Real(-1.0)
        model_cls = Model

        def fit_fn(self, ds):
            return {"mean": 0.0}

    model = MeanImpute().set_input(age_feature).fit(make_ds())
    loaded = stage_from_json(stage_to_json(model))
    assert type(loaded) is MeanImpute.Model
    assert loaded.transform_value(ft.Real(None)).value == 20.0


def test_subclass_in_type_override(age_feature):
    class TextStage(UnaryTransformer):
        in_type = ft.Text
        out_type = ft.Real

        def transform_value(self, v):
            return ft.Real(0.0)

    class PickListStage(TextStage):
        in_type = ft.PickList

    assert PickListStage.in_types == (ft.PickList,)
    with pytest.raises(TypeError):
        PickListStage().set_input(
            FeatureBuilder.Text("t").from_column().as_predictor())


def test_lambda_persistence_errors_at_save():
    f1 = FeatureBuilder.Real("a").from_column().as_predictor()
    lam = LambdaTransformer(lambda v: v, ft.Real).set_input(f1)
    with pytest.raises(ValueError, match="non-importable"):
        stage_to_json(lam)


def test_ragged_vector_column_raises():
    from transmogrifai_tpu.dataset import column_to_numpy
    with pytest.raises(ValueError, match="ragged"):
        column_to_numpy([(1.0, 2.0), (1.0,)], ft.OPVector)
    # all-empty and uniform widths still fine; empty row = zero vector
    arr = column_to_numpy([(1.0, 2.0), ()], ft.OPVector)
    assert arr.shape == (2, 2) and arr[1].tolist() == [0.0, 0.0]
