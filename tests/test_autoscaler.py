"""Elastic-fleet subsystem tests (PR 13).

Pins the autoscaler tentpole guarantees: the Holt/EMA arrival forecast
is bit-deterministic on a fixed series, the hysteresis policy never
flaps under oscillating load (injectable clock), scale-up provisions a
WARMED replica before it joins the placement ring (with the
``serving.scaler.provision`` fault retried on the seeded backoff and an
exhausted provision leaving the fleet serving at its current N),
scale-down only removes a replica after its queue fully drains (zero
accepted-request loss), the router's placement ring tracks elastic
growth/shrink mid-flight (a parked failover re-dispatch re-resolves
against the updated ring), re-priced admission sheds low-priority
traffic before scores, /statusz + /metricsz carry the scaler block and
``tm_fleet_scale_*`` families — and the headline ``faults``-marked
drill: a >=4x offered-load spike triggers PREDICTIVE scale-up, a
replica is hard-killed mid-scale-up, load subsides and the fleet scales
back down via drain, with zero accepted-request loss and the full
decision chain (forecast breach -> scale-up -> crash -> restart ->
scale-down) asserted from the flight-recorder dump artifact alone.
"""
import threading
import time
import urllib.request

import numpy as np
import pytest

from transmogrifai_tpu import Dataset, FeatureBuilder
from transmogrifai_tpu import models as M
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.resilience import faults
from transmogrifai_tpu.serving import (AdmissionController,
                                       ArrivalForecast,
                                       DeadlineUnmeetable, EngineConfig,
                                       FleetAutoscaler, FleetConfig,
                                       ScalerConfig, ScalingPolicy,
                                       ServingEngine, ServingFleet)
from transmogrifai_tpu.telemetry import recorder as trecorder
from transmogrifai_tpu.workflow import Workflow


def _train(seed: int):
    rng = np.random.default_rng(seed)
    n, d = 300, 5
    cols = {f"x{i}": rng.normal(size=n) for i in range(d)}
    y = (rng.random(n) < 1 / (1 + np.exp(-(cols["x0"] - cols["x1"])))
         ).astype(np.float64)
    cols["label"] = y
    schema = {f"x{i}": ft.Real for i in range(d)}
    schema["label"] = ft.RealNN
    ds = Dataset({k: np.asarray(v, np.float64) for k, v in cols.items()},
                 schema)
    label = (FeatureBuilder.of(ft.RealNN, "label")
             .from_column().as_response())
    preds = [FeatureBuilder.of(ft.Real, f"x{i}")
             .from_column().as_predictor() for i in range(d)]
    pred = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=2, candidates=[["LogisticRegression",
                                {"regParam": [0.01],
                                 "elasticNetParam": [0.0]}]]
    ).set_input(label, SanityChecker().set_input(
        label, transmogrify(preds)).output).output
    model = Workflow([pred]).train(ds)
    return model, ds


@pytest.fixture(scope="module")
def served():
    return _train(3)


@pytest.fixture(scope="module")
def served_v2():
    return _train(17)


def _pool(ds, seed=7, hi=9):
    rng = np.random.default_rng(seed)
    names = list(ds.column_names)
    ftypes = {k: ds.ftype(k) for k in names}
    return [Dataset({k: ds.column(k)[:s] for k in names}, ftypes)
            for s in rng.integers(1, hi, size=32)]


def _fleet(model, pool, replicas=2, **cfg_overrides):
    base = dict(replicas=replicas, supervise_s=0.05, breaker_open_s=0.3,
                restart_backoff_s=0.1, backoff_s=0.005)
    base.update(cfg_overrides)
    return ServingFleet(model, replicas=replicas, buckets=(16, 64),
                        warm_sample=pool[0], config=FleetConfig(**base),
                        engine_config=EngineConfig(max_wait_ms=2.0))


def _wait_until(pred, timeout=15.0, interval=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def _sample(q, w, n=2, ar=10.0, cr=10.0):
    return {"replicas": n, "queue_depth_mean": q, "wait_p99_ms": w,
            "arrival_rate": ar, "completion_rate": cr}


# ---------------------------------------------------------------------------
# config strictness
# ---------------------------------------------------------------------------

def test_scaler_config_strict_knobs():
    """Typo'd TM_SCALE_ name or unparsable value raises; explicit
    overrides win; every gate-disabling value is rejected at config
    time — an autoscaler whose knobs silently didn't apply is a static
    fleet pretending otherwise."""
    with pytest.raises(ValueError, match="TM_SCALE_TYPO"):
        ScalerConfig.from_env({"TM_SCALE_TYPO": "1"})
    with pytest.raises(ValueError, match="bad value"):
        ScalerConfig.from_env({"TM_SCALE_MAX_REPLICAS": "many"})
    cfg = ScalerConfig.from_env({"TM_SCALE_MAX_REPLICAS": "6"},
                                max_replicas=8)
    assert cfg.max_replicas == 8        # explicit override wins
    assert ScalerConfig.from_env(
        {"TM_SCALE_FORECAST": "ema"}).forecast == "ema"
    # validation zoo: each of these silently disables or inverts a
    # safety mechanism if accepted
    for bad in (dict(min_replicas=0),
                dict(max_replicas=1, min_replicas=2),
                dict(tick_s=0.0),
                dict(up_ticks=0),
                dict(down_queue_depth=8.0, up_queue_depth=8.0),
                dict(down_wait_p99_ms=50.0, up_wait_p99_ms=50.0),
                dict(step=0),
                dict(forecast="prophet"),
                dict(forecast_alpha=0.0),
                dict(forecast_beta=1.5),
                dict(headroom=0.0),
                dict(provision_attempts=0),
                dict(price_max=0.5),
                dict(cooldown_s=-1.0)):
        with pytest.raises(ValueError):
            ScalerConfig(**bad)


# ---------------------------------------------------------------------------
# forecast math (deterministic, no clocks)
# ---------------------------------------------------------------------------

def test_arrival_forecast_deterministic_and_modes():
    """The same fixed series produces BIT-identical level/trend/
    forecast in two independent instances; ema mode pins the trend to
    zero; off observes nothing; unseeded predicts None (never 'zero
    load ahead')."""
    series = [10.0, 10.0, 12.0, 20.0, 40.0, 80.0, 85.0]
    a = ArrivalForecast("holt", alpha=0.5, beta=0.3)
    b = ArrivalForecast("holt", alpha=0.5, beta=0.3)
    assert a.predict(4.0) is None       # unseeded: unknown, not 0
    for r in series:
        a.observe(r)
        b.observe(r)
    assert a.level == b.level and a.trend == b.trend
    assert a.predict(4.0) == b.predict(4.0)
    # a sustained ramp projects ABOVE the last observation: the trend
    # term is what makes pre-scaling "pre"
    assert a.trend > 0 and a.predict(4.0) > series[-1]

    e = ArrivalForecast("ema", alpha=0.5, beta=0.3)
    for r in series:
        e.observe(r)
    assert e.trend == 0.0               # ema mode: level-only
    assert e.predict(10.0) == e.predict(0.0)

    off = ArrivalForecast("off")
    off.observe(100.0)
    assert off.predict(1.0) is None and off.observations == 0

    neg = ArrivalForecast("holt", alpha=1.0, beta=1.0)
    neg.observe(100.0)
    neg.observe(0.0)
    assert neg.predict(50.0) == 0.0     # clamped, never negative


# ---------------------------------------------------------------------------
# hysteresis policy (pure, injectable clock)
# ---------------------------------------------------------------------------

def test_policy_no_flapping_under_oscillating_load():
    """Load alternating breach/calm every tick NEVER scales: each
    regime flip resets the opposing streak, so neither reaches its
    tick threshold — the hysteresis contract."""
    p = ScalingPolicy(ScalerConfig(up_ticks=2, down_ticks=2,
                                   forecast="off", max_replicas=4))
    now = 0.0
    for i in range(40):
        d = p.decide(_sample(20.0, 100.0) if i % 2
                     else _sample(0.0, 0.0), now)
        assert d["direction"] == "hold", (i, d)
        now += 0.25


def test_policy_band_ticks_reset_both_streaks():
    """A tick INSIDE the hysteresis band (neither breach nor calm) is
    evidence of neither regime: both streaks reset, so band-straddling
    noise cannot accumulate into a decision."""
    p = ScalingPolicy(ScalerConfig(up_ticks=2, down_ticks=2,
                                   forecast="off"))
    now = 0.0
    for _ in range(3):                          # breach, band, breach...
        d = p.decide(_sample(20.0, 100.0), now)
        assert d["direction"] == "hold"
        now += 0.25
        d = p.decide(_sample(4.0, 20.0), now)   # in the band
        assert d["direction"] == "hold" and d["up_streak"] == 0
        now += 0.25


def test_policy_hysteresis_up_down_cooldown_and_bounds():
    cfg = ScalerConfig(up_ticks=2, down_ticks=3, forecast="off",
                       min_replicas=1, max_replicas=3, cooldown_s=1.0)
    p = ScalingPolicy(cfg)
    now = 0.0
    assert p.decide(_sample(20.0, 100.0), now)["direction"] == "hold"
    d = p.decide(_sample(20.0, 100.0), now)
    assert d["direction"] == "up" and d["target_replicas"] == 3
    p.commit(now)
    # cooldown holds even under continued breach
    d = p.decide(_sample(20.0, 100.0), now + 0.5)
    assert d["direction"] == "hold" and d["reason"] == "cooldown"
    now += 1.5
    # at max: pressure cannot scale past the ceiling
    p.decide(_sample(20.0, 100.0, n=3), now)
    d = p.decide(_sample(20.0, 100.0, n=3), now)
    assert d["direction"] == "hold" and "max_replicas" in d["reason"]
    # calm for down_ticks: down, clamped at min
    for _ in range(2):
        assert p.decide(_sample(0.0, 0.0, n=3),
                        now)["direction"] == "hold"
    d = p.decide(_sample(0.0, 0.0, n=3), now)
    assert d["direction"] == "down" and d["target_replicas"] == 2
    p.commit(now)
    now += 1.5
    # at min: calm cannot scale below the floor
    for _ in range(3):
        d = p.decide(_sample(0.0, 0.0, n=1), now)
    assert d["direction"] == "hold"


def test_policy_forecast_prescales_before_pressure():
    """A ramping arrival rate triggers scale-up from the FORECAST while
    queue depth and waits are still calm — the predictive pre-scale the
    spike drill relies on. The reason names the forecast."""
    cfg = ScalerConfig(up_ticks=50, down_ticks=50, forecast="holt",
                       forecast_alpha=0.6, forecast_beta=0.4,
                       horizon_s=0.5, tick_s=0.25, replica_rps=30.0,
                       headroom=0.8, max_replicas=4)
    p = ScalingPolicy(cfg)
    now, d = 0.0, None
    # capacity 2x30x0.8 = 48 rps; ramp toward (and past) it
    for rate in (10.0, 20.0, 35.0, 55.0, 80.0, 110.0):
        d = p.decide(_sample(0.0, 0.0, ar=rate, cr=rate), now)
        now += 0.25
        if d["direction"] == "up":
            break
    assert d["direction"] == "up", d
    assert d["forecast_breach"] and d["reason"].startswith("forecast")
    assert d["up_streak"] < cfg.up_ticks    # pressure never got there


def test_policy_forecast_blocks_regrettable_scale_down():
    """Calm NOW but a forecast that still needs the current fleet
    holds the scale-down: a drain the horizon would immediately
    re-provision is thrash, not elasticity."""
    cfg = ScalerConfig(up_ticks=50, down_ticks=2, forecast="holt",
                       forecast_alpha=1.0, forecast_beta=0.0,
                       horizon_s=0.25, tick_s=0.25, replica_rps=30.0,
                       headroom=0.8, min_replicas=1, max_replicas=4)
    p = ScalingPolicy(cfg)
    now = 0.0
    # queues calm, but the arrival rate needs > 1 replica's capacity:
    # level pins to 40 rps (alpha=1) > 30x1x0.8 = 24 of a shrunken fleet
    for _ in range(5):
        d = p.decide(_sample(0.0, 0.0, n=2, ar=40.0, cr=40.0), now)
        assert d["direction"] == "hold", d
        now += 0.25
    assert "forecast" in d["reason"]
    # once the rate itself subsides, the same calm finally drains
    for _ in range(2):
        d = p.decide(_sample(0.0, 0.0, n=2, ar=5.0, cr=5.0), now)
        now += 0.25
    assert d["direction"] == "down"


def test_policy_max_bound_counts_dead_pending_restart_replicas():
    """A crashed replica comes back via the supervisor: the max bound
    is judged on TOTAL non-draining replicas (dead included), so
    pressure while one is briefly dead cannot push the fleet above the
    budget the moment it restarts."""
    cfg = ScalerConfig(up_ticks=1, down_ticks=2, forecast="off",
                       min_replicas=1, max_replicas=2, cooldown_s=0.0)
    p = ScalingPolicy(cfg)
    s = _sample(20.0, 100.0, n=1)       # 1 live...
    s["total_replicas"] = 2             # ...but 2 owned (1 dead)
    d = p.decide(s, 0.0)
    assert d["direction"] == "hold" and "max_replicas" in d["reason"]
    # with room under the cap, the target counts the dead one too
    p3 = ScalingPolicy(ScalerConfig(up_ticks=1, down_ticks=2,
                                    forecast="off", max_replicas=3,
                                    cooldown_s=0.0))
    d = p3.decide(s, 0.0)
    assert d["direction"] == "up" and d["target_replicas"] == 3


def test_policy_learns_capacity_from_peak_completion_rate():
    p = ScalingPolicy(ScalerConfig(forecast="off", replica_rps=0.0))
    now = 0.0
    for cr in (10.0, 60.0, 40.0):
        p.decide(_sample(0.0, 0.0, n=2, ar=cr, cr=cr), now)
        now += 0.25
    assert p.capacity_rps() == 30.0     # peak per-replica, not last


# ---------------------------------------------------------------------------
# re-priced admission (the load-adaptive upgrade)
# ---------------------------------------------------------------------------

def test_admission_reprice_sheds_low_priority_before_scores():
    """Under pressure (price > 1) a low-priority request trips
    DeadlineUnmeetable while a NORMAL request with the same deadline
    still admits; at rest (price 1.0) the classes are
    indistinguishable. This is the shed-explanations-before-scores
    ordering the LOCO workload (ROADMAP item 5) will ride."""
    a = AdmissionController()
    a.ema.update(10, 0.050)             # estimate(10) = 100 ms
    now = time.monotonic()
    deadline = now + 0.150
    a.set_price(1.2)
    a.admit(10, deadline, 0, 0, now=now)                # 120 < 150 ms
    with pytest.raises(DeadlineUnmeetable, match="priority low"):
        a.admit(10, deadline, 0, 0, now=now, priority="low")  # 480 ms
    # at rest: low admits exactly like normal
    a.set_price(1.0)
    a.admit(10, deadline, 0, 0, now=now, priority="low")
    # price climbs shedding ALL deadline traffic before queues saturate
    a.set_price(4.0)
    with pytest.raises(DeadlineUnmeetable):
        a.admit(10, deadline, 0, 0, now=now)


def test_admission_price_clamps_and_priority_validates():
    a = AdmissionController()
    assert a.set_price(0.25) == 1.0     # never optimistic-beyond-EMA
    assert a.set_price(3.0) == 3.0
    with pytest.raises(ValueError, match="unknown admission priority"):
        a.admit(1, None, 0, 0, priority="urgent")
    with pytest.raises(ValueError):
        AdmissionController(low_priority_factor=0.5)


def test_engine_threads_priority_to_admission(served):
    """engine.submit(priority=...) reaches the controller: with a
    re-priced margin, a low-priority deadline request is rejected at
    the door while the same-deadline normal request scores."""
    model, ds = served
    pool = _pool(ds)
    with ServingEngine(model, buckets=(16, 64),
                       warm_sample=pool[0]) as eng:
        for i in range(6):              # seed the EMA
            eng.score(pool[i % len(pool)], timeout=60)
        est = eng.admission.ema.estimate(pool[0].n_rows)
        assert est is not None and est > 0
        eng.admission.set_price(1.5)    # margins: normal 1.5x, low 6x
        deadline_ms = est * 3.0 * 1e3   # between 1.5x and 6x the EMA
        out = eng.score(pool[0], timeout=60, deadline_ms=deadline_ms)
        assert out                      # normal traffic still scores
        with pytest.raises(DeadlineUnmeetable):
            eng.submit(pool[0], deadline_ms=deadline_ms, priority="low")
        assert eng.stats.as_dict()["rejected_predicted_late"] >= 1


# ---------------------------------------------------------------------------
# elastic fleet topology
# ---------------------------------------------------------------------------

def test_add_replica_joins_warm_and_takes_traffic(served):
    model, ds = served
    pool = _pool(ds)
    with _fleet(model, pool, replicas=2) as fleet:
        fleet.score(pool[0], timeout=60)
        name = fleet.add_replica()
        assert name == "r2"
        h = fleet._handle(name)
        # warmed BEFORE joining the ring: by the time any request can
        # route here, the engine is ready and every bucket compiled
        assert h.engine.ready()
        compiles = sum(
            v.backend.stats.total_compiles
            for v in [h.engine.registry.get()])
        assert compiles >= 2            # both buckets warm
        futs = [fleet.submit(pool[i % len(pool)]) for i in range(48)]
        assert all(f.exception(timeout=60) is None for f in futs)
        assert fleet.stats.as_dict()["dispatches"].get(name, 0) > 0
        st = fleet.status()
        assert st["replica_count"] == 3
        assert st["replicas"][name]["supervision"]["draining"] is False
        assert fleet.stats.as_dict()["replicas_added"] == 1


def test_remove_replica_drains_fully_before_removal(served):
    """Scale-down-only-when-drained: requests queued on the draining
    replica (fat max_wait so they SIT queued) all complete; the handle
    leaves only after its engine's ledger balances; the router never
    sees it again."""
    model, ds = served
    pool = _pool(ds)
    fleet = ServingFleet(
        model, replicas=2, buckets=(16, 64), warm_sample=pool[0],
        config=FleetConfig(replicas=2, supervise_s=0.05),
        engine_config=EngineConfig(max_wait_ms=400.0))
    with fleet:
        fleet.score(pool[0], timeout=60)
        futs = [fleet.submit(pool[i % len(pool)]) for i in range(16)]
        victim = fleet._handle("r1")
        fleet.remove_replica("r1")      # drains, then removes
        # zero accepted-request loss across the scale-down
        assert all(f.exception(timeout=60) is None for f in futs)
        eng = victim.engine.stats.as_dict()
        assert eng["queue_depth_requests"] == 0
        assert eng["submitted"] == eng["completed"]     # fully drained
        assert [h.name for h in fleet.replica_handles()] == ["r0"]
        assert "r1" not in fleet.router.breakers_dict()
        assert fleet.stats.as_dict()["replicas_removed"] == 1
        with pytest.raises(KeyError):
            fleet.remove_replica("r1")  # already gone
        with pytest.raises(ValueError, match="last live replica"):
            fleet.remove_replica("r0")  # never scale to zero
        # the fleet still serves
        fleet.score(pool[1], timeout=60)


def test_parked_failover_redispatch_resolves_against_updated_ring(served):
    """The satellite fix: a request parked in the failover backoff heap
    re-resolves against the UPDATED ring when its re-dispatch fires —
    a replica drained/removed while it slept is simply not a candidate,
    and the request completes instead of burning attempts on a
    draining replica until the caller sees an error."""
    model, ds = served
    pool = _pool(ds)
    with _fleet(model, pool, replicas=2, backoff_s=0.25,
                route_attempts=4) as fleet:
        fleet.score(pool[0], timeout=60)
        # draining replicas leave the candidate ring immediately
        h1 = fleet._handle("r1")
        h1.draining = True
        try:
            assert [h.name for h in fleet.router.candidates(None)] \
                == ["r0"]
        finally:
            h1.draining = False
        # park a request (attempt 1 fails at the route fault, backoff
        # ~0.25 s), then shrink the ring while it sleeps
        with faults.active("serving.router.route:raise-transient:1"):
            fut = fleet.submit(pool[0])
            t = threading.Thread(target=fleet.remove_replica,
                                 args=("r1",))
            t.start()
            assert fut.exception(timeout=60) is None    # completed
            t.join(30)
        assert [h.name for h in fleet.replica_handles()] == ["r0"]
        # ...and growth mid-flight: a new replica is routable at once
        name = fleet.add_replica()
        assert name in [h.name for h in fleet.router.candidates(None)]
        futs = [fleet.submit(pool[i % len(pool)]) for i in range(32)]
        assert all(f.exception(timeout=60) is None for f in futs)
        assert fleet.stats.as_dict()["dispatches"].get(name, 0) > 0


def test_remove_dead_replica_is_never_resurrected(served):
    """Removing a DEAD replica (crashed, supervisor restart pending)
    must suppress the scheduled restart: the draining flag and the
    supervisor's restart branch serialize on the life lock, so a
    removed replica's engine can never be started into a handle-less
    zombie no fleet.stop() would ever stop."""
    model, ds = served
    pool = _pool(ds)
    with _fleet(model, pool, replicas=2,
                restart_backoff_s=0.3) as fleet:
        fleet.score(pool[0], timeout=60)
        victim = fleet._handle("r1")
        fleet.chaos_kill("r1", reason="test: dead before removal")
        fleet.remove_replica("r1")      # dead: no drain, just removed
        assert [h.name for h in fleet.replica_handles()] == ["r0"]
        time.sleep(0.8)                 # well past restart_at
        assert fleet.stats.as_dict()["replica_restarts"] == 0
        assert not victim.engine.live()
        fleet.score(pool[1], timeout=60)    # still serving on r0
    """A replicas=1 fleet may legally hold a prebuilt scorer
    (degenerate fleet == one engine) — but GROWING it would share that
    one mutable backend across two failure domains: the constructor's
    shared-nothing guard re-runs at the new topology size."""
    model, _ = served
    scorer = model.compile_scoring(buckets=(32,))
    fleet = ServingFleet(scorer, replicas=1, warm=False)
    with pytest.raises(ValueError, match="shared-nothing"):
        fleet.add_replica()
    assert len(fleet.replica_handles()) == 1


def test_rollout_commit_repoints_elastic_provisioning(served, served_v2):
    """A replica added AFTER a committed rollout serves the PROMOTED
    model, not the construction-time one — the commit re-points the
    fleet's provisioning source."""
    model, ds = served
    model2, _ = served_v2
    pool = _pool(ds)
    with _fleet(model, pool, replicas=2,
                rollout_min_requests=4, rollout_bake_s=0.5) as fleet:
        fleet.score(pool[0], timeout=60)
        report = fleet.rollout("v2", model2)
        assert not report["rolled_back"], report
        name = fleet.add_replica()
        h = fleet._handle(name)
        assert h.engine.registry.default_version == "v2"
        futs = [fleet.submit(pool[i % len(pool)]) for i in range(8)]
        assert all(f.exception(timeout=60) is None for f in futs)


# ---------------------------------------------------------------------------
# the autoscaler loop (fault points, surfaces)
# ---------------------------------------------------------------------------

def _scaler_cfg(**overrides):
    base = dict(min_replicas=1, max_replicas=3, tick_s=0.05,
                up_queue_depth=2.0, up_wait_p99_ms=30.0,
                down_queue_depth=0.5, down_wait_p99_ms=5.0,
                up_ticks=2, down_ticks=6, cooldown_s=0.2,
                forecast="off", replica_rps=100.0,
                provision_backoff_s=0.02)
    base.update(overrides)
    return ScalerConfig(**base)


def test_scaler_tick_fault_drops_one_evaluation_not_the_loop(served):
    model, ds = served
    pool = _pool(ds)
    with _fleet(model, pool, replicas=1) as fleet:
        fleet.score(pool[0], timeout=60)
        sc = FleetAutoscaler(fleet, _scaler_cfg())
        with faults.active("serving.scaler.tick:raise-fatal:2"):
            with sc:
                assert _wait_until(
                    lambda: sc.stats.as_dict()["evaluations_dropped"]
                    >= 1, timeout=10)
                # the loop survived its dropped evaluation and kept
                # evaluating afterwards
                base = sc.stats.as_dict()["evaluations"]
                assert _wait_until(
                    lambda: sc.stats.as_dict()["evaluations"]
                    > base + 2, timeout=10)
        st = sc.stats.as_dict()
        assert st["evaluations_dropped"] == 1
        assert st["evaluations"] >= 3


def test_scaler_provision_fault_retried_then_exhausted(served):
    """A transient provision fault is retried on the seeded backoff and
    the scale-up COMPLETES; an exhausted provision abandons this
    scale-up with the fleet serving untouched at its current N."""
    model, ds = served
    pool = _pool(ds)
    with _fleet(model, pool, replicas=1) as fleet:
        fleet.score(pool[0], timeout=60)
        cfg = _scaler_cfg(up_queue_depth=0.5, up_wait_p99_ms=1.0,
                          down_queue_depth=0.1, down_wait_p99_ms=0.5,
                          down_ticks=10_000, provision_attempts=2)
        sc = FleetAutoscaler(fleet, cfg)
        # sustained submits keep queue/wait pressure over the (tiny)
        # thresholds so the policy decides up almost immediately
        stop = threading.Event()
        futs = []

        def pump():
            i = 0
            while not stop.is_set():
                futs.append(fleet.submit(pool[i % len(pool)]))
                i += 1
                time.sleep(0.002)

        t = threading.Thread(target=pump)
        with faults.active("serving.scaler.provision:raise-transient:1"):
            with sc:
                t.start()
                assert _wait_until(
                    lambda: sc.stats.as_dict()["replicas_added"] >= 1,
                    timeout=20)
        st = sc.stats.as_dict()
        assert st["provision_retries"] == 1     # attempt 1 faulted
        assert st["provision_failures"] == 0
        assert len(fleet.replica_handles()) == 2

        # second scaler: every provision attempt dies -> the scale-up
        # is abandoned, the fleet keeps serving at its current N
        sc2 = FleetAutoscaler(fleet, cfg)
        with faults.active("serving.scaler.provision:raise-fatal:1+"):
            with sc2:
                assert _wait_until(
                    lambda: sc2.stats.as_dict()["provision_failures"]
                    >= 1, timeout=20)
        stop.set()
        t.join(10)
        assert len(fleet.replica_handles()) == 2    # N unchanged
        assert all(f.exception(timeout=60) is None for f in futs)


def test_statusz_and_metricsz_carry_scaler_surfaces(served):
    """HealthServer(scaler) duck-types: /statusz gains the scaler block
    (state, current/target N, last decision + reason, forecast) and
    /metricsz emits tm_fleet_scale_events_total{direction=} +
    tm_fleet_target_replicas alongside the per-replica admission
    price."""
    import json as _json

    model, ds = served
    pool = _pool(ds)
    from transmogrifai_tpu.serving import HealthServer
    with _fleet(model, pool, replicas=1) as fleet:
        fleet.score(pool[0], timeout=60)
        sc = FleetAutoscaler(fleet, _scaler_cfg(forecast="holt"))
        with sc:
            assert _wait_until(
                lambda: sc.stats.as_dict()["evaluations"] >= 2,
                timeout=10)
            server = HealthServer(sc).start()
            try:
                port = server.port
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/statusz") as r:
                    doc = _json.loads(r.read())
                blk = doc["scaler"]
                assert blk["state"] in ("steady", "cooldown",
                                        "scaling_up", "scaling_down")
                assert blk["live_replicas"] == 1
                assert blk["target_replicas"] == 1
                assert blk["forecast"]["mode"] == "holt"
                assert "last_decision" in blk and "price" in blk
                assert doc["replica_count"] == 1
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metricsz") as r:
                    text = r.read().decode()
            finally:
                server.stop()
        assert 'tm_fleet_scale_events_total{direction="up"} 0' in text
        assert 'tm_fleet_scale_events_total{direction="down"} 0' in text
        assert "tm_fleet_target_replicas 1" in text
        assert "tm_fleet_live_replicas 1" in text
        assert 'tm_engine_admission_price{replica="r0"} 1.0' in text
        assert "tm_scaler_ticks_total" in text
        assert "tm_scaler_capacity_rps 100.0" in text
        # counters end _total and every family is typed (the /metricsz
        # grammar contract, same as test_telemetry pins globally)
        for line in text.splitlines():
            if line.startswith("tm_scaler_") and "_total" in line \
                    and not line.startswith("#"):
                name = line.split("{")[0].split(" ")[0]
                assert f"# TYPE {name} counter" in text


def test_scaler_repricing_pushes_admission_price(served):
    """Sustained wait pressure re-prices every live replica's admission
    controller above 1.0 — and the price RELAXES back once the
    pressure clears (a permanently-inflated margin would shed forever
    after one bad minute)."""
    model, ds = served
    pool = _pool(ds)
    with _fleet(model, pool, replicas=1) as fleet:
        fleet.score(pool[0], timeout=60)
        # max_replicas=1: no scaling, isolate the re-pricer
        cfg = _scaler_cfg(max_replicas=1, up_wait_p99_ms=2.0,
                          down_wait_p99_ms=0.5, target_wait_ms=2.0)
        sc = FleetAutoscaler(fleet, cfg)
        h = fleet._handle("r0")
        stop = threading.Event()
        futs = []

        def pump():
            i = 0
            while not stop.is_set():
                futs.append(fleet.submit(pool[i % len(pool)]))
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=pump)
        with sc:
            t.start()
            assert _wait_until(
                lambda: h.engine.admission.price > 1.0, timeout=15)
            assert sc.stats.as_dict()["reprices"] >= 1
            stop.set()
            t.join(10)
            for f in futs:
                f.exception(timeout=60)
            assert _wait_until(
                lambda: h.engine.admission.price == 1.0, timeout=15)
        # stop() RELEASES the margin: a scaler stopped mid-spike must
        # not leave the fleet shedding at its last inflated price
        # forever (nothing else would ever set it back)
        h.engine.admission.set_price(5.0)
        sc.stop()
        assert h.engine.admission.price == 1.0


# ---------------------------------------------------------------------------
# THE DRILL: spike -> predictive scale-up -> kill mid-scale-up ->
#            scale-down via drain, chain asserted from the dump alone
# ---------------------------------------------------------------------------

@pytest.mark.faults
def test_elastic_spike_drill_chain_from_flight_dump(
        served, tmp_path, monkeypatch):
    """The PR 13 acceptance drill: a >=4x offered-load spike triggers
    PREDICTIVE scale-up (the forecast breach, not the pressure streak —
    up_ticks is set unreachably high), a replica is hard-killed
    mid-scale-up (the provision hang fault holds the scale-up open),
    the supervisor restarts it, load subsides and the fleet scales back
    down via drain. Zero accepted-request loss, router ledgers
    reconcile, and the FULL decision chain — forecast-reasoned
    scale-up decision -> provision fault -> replica crash -> restart ->
    provisioned -> scale-down decision -> drained removal — is
    asserted from the flight-recorder dump artifact ALONE, in recorder
    order."""
    model, ds = served
    pool = _pool(ds, hi=5)
    monkeypatch.setenv("TM_FLIGHT_DIR", str(tmp_path))
    trecorder.RECORDER.clear()
    base_rps, spike_rps = 25.0, 110.0       # 4.4x
    cfg = ScalerConfig(
        min_replicas=2, max_replicas=3, tick_s=0.05,
        # pressure path fenced off: only the FORECAST can scale up
        up_ticks=10_000, up_queue_depth=1e9, up_wait_p99_ms=1e9,
        down_queue_depth=2.0, down_wait_p99_ms=20.0, down_ticks=6,
        cooldown_s=0.3, forecast="holt", forecast_alpha=0.6,
        forecast_beta=0.4, horizon_s=0.2, replica_rps=50.0,
        headroom=0.8, provision_attempts=2, provision_backoff_s=0.05)
    # capacity 2 x 50 x 0.8 = 80 rps: base 25 is comfortable, the
    # spike's 110 breaches the projection within a few ticks
    events = trecorder.RECORDER.events

    def seen(subsystem, name, **attrs):
        for e in events(subsystem):
            if e["event"] == name and all(
                    (e.get("attrs") or {}).get(k) == v
                    for k, v in attrs.items()):
                return True
        return False

    futs = []
    with _fleet(model, pool, replicas=2) as fleet:
        for i in range(8):
            fleet.score(pool[i % len(pool)], timeout=60)
        sc = FleetAutoscaler(fleet, cfg)
        with faults.active("serving.scaler.provision:hang:1:0.5"):
            with sc:
                t0 = time.monotonic()
                i = 0

                def drive(rps, until):
                    nonlocal i
                    while time.monotonic() < until:
                        futs.append(fleet.submit(pool[i % len(pool)]))
                        i += 1
                        time.sleep(1.0 / rps)

                drive(base_rps, t0 + 0.6)       # seed the forecast
                # SPIKE until the scale-up decision lands...
                deadline = time.monotonic() + 15.0
                killed = False
                while time.monotonic() < deadline:
                    drive(spike_rps, time.monotonic() + 0.05)
                    if not killed and faults.STATS.as_dict()[
                            "arrivals"].get(
                                "serving.scaler.provision", 0) >= 1:
                        # the provision hang is IN FLIGHT: this is
                        # mid-scale-up — hard-kill a serving replica
                        # (the same path serving.replica.crash drives)
                        fleet.chaos_kill("r0",
                                         reason="drill: mid-scale-up")
                        killed = True
                    if killed and seen("scaler", "replica.provisioned"):
                        break
                assert killed, "provision window never opened"
                assert seen("scaler", "replica.provisioned"), \
                    "scale-up never completed"
                # restart before calm: keep a trickle flowing
                deadline = time.monotonic() + 15.0
                while time.monotonic() < deadline and not seen(
                        "fleet", "replica.restart"):
                    drive(base_rps, time.monotonic() + 0.1)
                # CALM: light load until the fleet scales back down
                deadline = time.monotonic() + 20.0
                while time.monotonic() < deadline and not seen(
                        "fleet", "replica.remove"):
                    drive(8.0, time.monotonic() + 0.1)
        # ZERO accepted-request loss: every submitted future resolves
        # with scores — through the spike, the kill, and the drain
        assert all(f.exception(timeout=120) is None for f in futs)
        fl = fleet.stats.as_dict()
        assert fl["routed"] == len(futs) + 8        # + the warm-ups
        assert fl["routed"] == fl["completed"]      # failed==cancelled==0
        assert fl["failed"] == 0 and fl["cancelled"] == 0
        assert fl["replica_crashes"] == 1 and fl["replica_restarts"] >= 1
        assert len(fleet.replica_handles()) == 2    # back at baseline
    # fleet.stop() auto-dumped the ring: reconstruct the WHOLE chain
    # from the artifact alone
    path = trecorder.RECORDER.last_dump_path
    assert path and str(tmp_path) in path
    dump = trecorder.load_dump(path)

    def idx(pred, what):
        for j, e in enumerate(dump):
            if pred(e):
                return j
        raise AssertionError(f"{what} not in dump")

    i_up = idx(lambda e: e["subsystem"] == "scaler"
               and e["event"] == "scale.decision"
               and e["attrs"]["direction"] == "up", "scale-up decision")
    up = dump[i_up]["attrs"]
    assert up["reason"].startswith("forecast"), up  # PREDICTIVE, by name
    assert up["predicted_rps"] > up["capacity_rps"] * 2 * 0.8
    assert up["target_replicas"] == 3
    i_fault = idx(lambda e: e["subsystem"] == "faults"
                  and e["event"] == "injected"
                  and e["attrs"]["point"] == "serving.scaler.provision",
                  "provision fault")
    i_crash = idx(lambda e: e["subsystem"] == "fleet"
                  and e["event"] == "replica.crash"
                  and e["attrs"]["replica"] == "r0", "crash")
    i_restart = idx(lambda e: e["subsystem"] == "fleet"
                    and e["event"] == "replica.restart"
                    and e["attrs"]["replica"] == "r0", "restart")
    i_prov = idx(lambda e: e["subsystem"] == "scaler"
                 and e["event"] == "replica.provisioned", "provisioned")
    i_down = idx(lambda e: e["subsystem"] == "scaler"
                 and e["event"] == "scale.decision"
                 and e["attrs"]["direction"] == "down",
                 "scale-down decision")
    i_rm = idx(lambda e: e["subsystem"] == "fleet"
               and e["event"] == "replica.remove", "removal")
    # the causal chain, in recorder order: the decision precedes the
    # provision fault, the crash lands mid-scale-up (before the
    # provisioned event), restart follows the crash, and the
    # scale-down (and its drained removal) close the incident
    assert i_up < i_fault < i_prov
    assert i_up < i_crash < i_prov      # killed MID-scale-up
    assert i_crash < i_restart
    assert max(i_prov, i_restart) < i_down < i_rm
    assert dump[i_down]["attrs"]["target_replicas"] == 2
    assert dump[i_rm]["attrs"]["replica"] == dump[idx(
        lambda e: e["subsystem"] == "fleet"
        and e["event"] == "replica.drain", "drain")]["attrs"]["replica"]
