"""Bucketed, double-buffered fused serving pipeline tests.

Pins the tentpole guarantees: bucket padding never changes scores
(bitwise on CPU), the compile universe is bounded by len(buckets) across
an arbitrary batch-size mix (asserted via the trace-time compile
counters), and score_stream re-raises producer exceptions positionally.
"""
import csv
import json
import os

import numpy as np
import pytest

from serving_util import train_small_serving_model

from transmogrifai_tpu import Dataset
from transmogrifai_tpu.workflow import (DEFAULT_SCORE_BUCKETS, Workflow,
                                        _normalize_buckets)


@pytest.fixture(scope="module")
def served():
    """One small all-numeric fused model + its dataset (trained once)."""
    return train_small_serving_model(3)


def _slice(ds, n0, n1):
    return Dataset({k: ds.column(k)[n0:n1] for k in ds.column_names},
                   {k: ds.ftype(k) for k in ds.column_names})


def test_normalize_buckets():
    assert _normalize_buckets(None) is None
    assert _normalize_buckets(True) == DEFAULT_SCORE_BUCKETS
    assert _normalize_buckets([128, 32, 32]) == (32, 128)
    with pytest.raises(ValueError):
        _normalize_buckets([0, 64])
    with pytest.raises(ValueError):
        _normalize_buckets([])


def test_bucket_slices_cover_and_bound(served):
    model, ds, _ = served
    scorer = model.compile_scoring(buckets=(32, 128))
    # remainder pads to the smallest fitting bucket; oversize batches
    # split into top-bucket slices + a padded remainder
    assert list(scorer._bucket_slices(7)) == [(0, 7, 32)]
    assert list(scorer._bucket_slices(128)) == [(0, 128, 128)]
    assert list(scorer._bucket_slices(300)) == [
        (0, 128, 128), (128, 256, 128), (256, 300, 128)]
    # unbucketed: one exact-shape slice (classic per-shape jit)
    naive = model.compile_scoring()
    assert list(naive._bucket_slices(300)) == [(0, 300, 300)]


def test_bucket_padding_never_changes_scores(served):
    """Row-exact (bitwise, CPU) parity: padded buckets vs exact shapes."""
    model, ds, pred_name = served
    naive = model.compile_scoring()
    bucketed = model.compile_scoring(buckets=(32, 64, 128))
    for n in (1, 7, 33, 100, 300):          # 300 > top bucket: splits
        chunk = _slice(ds, 0, n)
        ref = naive.score_arrays(chunk)
        got = bucketed.score_arrays(chunk)
        assert set(ref) == set(got)
        for k in ref:
            assert ref[k].shape == got[k].shape
            assert np.array_equal(ref[k], got[k]), (n, k)
    assert bucketed.stats.total_padded_rows > 0  # padding really ran


def test_compile_count_bounded_over_randomized_mix(served):
    """>= 8 distinct batch sizes through score_stream compile at most
    len(buckets) fused programs; the naive scorer compiles one per
    distinct shape. Results stay bitwise-equal to per-batch
    score_arrays."""
    model, ds, _ = served
    rng = np.random.default_rng(11)
    sizes = []
    while len(set(sizes)) < 8:
        sizes = [int(s) for s in rng.integers(1, 200, size=12)]
    chunks = [_slice(ds, 0, s) for s in sizes]

    naive = model.compile_scoring()
    refs = [naive.score_arrays(c) for c in chunks]
    assert naive.stats.total_compiles == len(set(sizes))

    buckets = (32, 64, 128, 256)
    scorer = model.compile_scoring(buckets=buckets)
    outs = list(scorer.score_stream(iter(chunks)))
    assert len(outs) == len(chunks)
    for ref, got in zip(refs, outs):
        for k in ref:
            assert np.array_equal(ref[k], got[k])
    assert 0 < scorer.stats.total_compiles <= len(buckets)
    # compiled shapes are bucket members, never raw traffic shapes
    assert set(scorer.stats.compiles) <= set(buckets)
    # a second pass compiles NOTHING new
    before = scorer.stats.total_compiles
    list(scorer.score_stream(iter(chunks)))
    assert scorer.stats.total_compiles == before
    # counters add up: every real row accounted once
    assert scorer.stats.total_rows == 2 * sum(sizes)


def test_empty_chunk_stays_inside_bucket_universe(served):
    """A zero-row chunk (upstream filter matched nothing) pads to the
    smallest bucket instead of compiling an extra shape-0 program."""
    model, ds, pred_name = served
    scorer = model.compile_scoring(buckets=(32, 64))
    out = scorer.score_arrays(_slice(ds, 0, 0))
    assert out[pred_name].shape[0] == 0
    assert set(scorer.stats.compiles) <= {32, 64}
    # a real batch afterwards reuses the same program
    scorer.score_arrays(_slice(ds, 0, 10))
    assert scorer.stats.total_compiles == 1


def test_score_stream_reraises_producer_exception_positionally(served):
    """Chunks before the failing position yield results first; then the
    producer's exception surfaces (for both threaded and inline hosts)."""
    model, ds, _ = served

    for host_thread in (True, False):
        def chunks():
            yield _slice(ds, 0, 16)
            yield _slice(ds, 16, 48)
            raise RuntimeError("source went away")

        scorer = model.compile_scoring(buckets=(32, 64))
        it = scorer.score_stream(chunks(), host_thread=host_thread)
        got = []
        with pytest.raises(RuntimeError, match="source went away"):
            for out in it:
                got.append(out)
        assert len(got) == 2, f"host_thread={host_thread}"
        ref = model.compile_scoring().score_arrays(_slice(ds, 16, 48))
        for k in ref:
            assert np.array_equal(ref[k], got[1][k])


def test_scoring_stats_dict(served):
    model, ds, _ = served
    scorer = model.compile_scoring(buckets=(64, 256))
    scorer.score_arrays(_slice(ds, 0, 50))
    scorer.score_arrays(_slice(ds, 0, 200))
    d = scorer.stats.as_dict()
    assert d["per_bucket"]["64"]["rows"] == 50
    assert d["per_bucket"]["64"]["padded_rows"] == 14
    assert d["per_bucket"]["256"]["padded_rows"] == 56
    assert d["total_compiles"] == 2
    assert 0.0 < d["padding_overhead"] < 1.0
    assert d["seconds"] > 0
    assert d["rows_per_sec"] > 0
    json.dumps(d)    # JSON-ready for bench / serve CLI


def test_donated_buffers_still_exact(served):
    model, ds, pred_name = served
    ref = model.compile_scoring().score_arrays(_slice(ds, 0, 40))
    donating = model.compile_scoring(buckets=(64,), donate=True)
    got = donating.score_arrays(_slice(ds, 0, 40))
    assert np.array_equal(ref[pred_name], got[pred_name])


def test_portable_export_records_bucket_metadata(served, tmp_path):
    model, _, _ = served
    out = str(tmp_path / "artifact")
    model.export_portable(out, buckets=(512, 2048))
    with open(os.path.join(out, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["scoreBuckets"] == [512, 2048]
    from transmogrifai_tpu import portable
    pm = portable.load(out)
    assert pm.score_buckets == (512, 2048)
    # absent metadata (older artifacts / unbucketed export) stays None
    out2 = str(tmp_path / "artifact2")
    model.export_portable(out2)
    assert portable.load(out2).score_buckets is None


def test_serve_cli_stream_scores_csv(served, tmp_path):
    """End-to-end serve entry: saved model + label-free CSV in, scores
    CSV + stats JSON out, bitwise-equal to direct fused scoring."""
    from transmogrifai_tpu.cli import main as cli_main

    model, ds, pred_name = served
    model_dir = str(tmp_path / "model")
    model.save(model_dir)
    in_csv = str(tmp_path / "in.csv")
    feature_cols = [c for c in ds.column_names if c != "label"]
    with open(in_csv, "w", newline="") as f:
        wr = csv.writer(f)
        # whitespace-padded header: columns must still map to features
        # (not silently parse as all-null under the raw DictReader keys)
        wr.writerow([f" {c}" if i % 2 else c
                     for i, c in enumerate(feature_cols)])
        for i in range(ds.n_rows):
            wr.writerow(["" if np.isnan(ds.column(c)[i])
                         else repr(float(ds.column(c)[i]))
                         for c in feature_cols])
    out_csv = str(tmp_path / "scores.csv")
    stats_json = str(tmp_path / "stats.json")
    rc = cli_main(["serve", "--model", model_dir, "--input", in_csv,
                   "--output", out_csv, "--chunk-rows", "96",
                   "--buckets", "32,128", "--stats-json", stats_json])
    assert rc == 0
    with open(stats_json) as f:
        summary = json.load(f)
    assert summary["rows"] == ds.n_rows
    assert summary["buckets"] == [32, 128]
    assert summary["stats"]["total_compiles"] <= 2
    with open(out_csv) as f:
        rows = list(csv.reader(f))
    assert rows[0][-2:] == [f"{pred_name}_0", f"{pred_name}_1"]
    assert len(rows) - 1 == ds.n_rows
    probs = model.compile_scoring().score_arrays(ds)[pred_name]
    got = np.array([[float(v) for v in r[-2:]] for r in rows[1:]])
    np.testing.assert_allclose(got, probs, atol=1e-6)


def test_double_buffer_primitive():
    from transmogrifai_tpu.io.stream import double_buffer

    calls = []
    out = list(double_buffer(range(5), lambda x: calls.append(x) or x * 2,
                             lambda x: x + 1, depth=2))
    assert out == [1, 3, 5, 7, 9]
    assert calls == [0, 1, 2, 3, 4]

    def bad():
        yield 1
        yield 2
        raise KeyError("boom")

    got = []
    with pytest.raises(KeyError):
        for v in double_buffer(bad(), lambda x: x, lambda x: x, depth=3):
            got.append(v)
    assert got == [1, 2]      # the produced prefix still surfaced
    with pytest.raises(ValueError):
        list(double_buffer(range(3), lambda x: x, lambda x: x, depth=0))
