// Native runtime for transmogrifai_tpu: CSV columnar loader + batch hashing.
//
// Reference parity: the upstream JVM stack leans on native code for IO and
// hashing (Hadoop native readers, lz4/snappy codecs, Spark's unsafe row
// parsing, MurmurHash3 in HashingTF). This library is the TPU build's
// host-side equivalent: it turns a CSV file into columnar buffers (numeric
// columns parsed straight to float64, string columns exposed as one
// contiguous buffer + offsets) and hashes token batches, both without
// creating per-cell Python objects. Loaded via ctypes; the Python layer
// falls back to pure Python when the shared library is unavailable.
//
// RFC 4180-style parsing: quoted fields, escaped quotes (""), CRLF.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <atomic>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct CsvTable {
  std::vector<std::string> header;
  // cells stored column-major in one arena per column
  std::vector<std::string> arena;        // per column: concatenated bytes
  std::vector<std::vector<int64_t>> offsets;  // per column: n_rows+1 offsets
  int64_t n_rows = 0;
};

// parse one record (handles quotes); returns fields; advances *p
bool parse_record(const char** p, const char* end, char delim,
                  std::vector<std::string>* fields) {
  fields->clear();
  if (*p >= end) return false;
  std::string cur;
  const char* s = *p;
  bool in_quotes = false;
  for (;;) {
    if (s >= end) {
      fields->push_back(cur);
      *p = s;
      return true;
    }
    char c = *s;
    if (in_quotes) {
      if (c == '"') {
        if (s + 1 < end && s[1] == '"') { cur.push_back('"'); s += 2; continue; }
        in_quotes = false; s++; continue;
      }
      cur.push_back(c); s++; continue;
    }
    if (c == '"' && cur.empty()) { in_quotes = true; s++; continue; }
    if (c == delim) { fields->push_back(cur); cur.clear(); s++; continue; }
    if (c == '\n' || c == '\r') {
      fields->push_back(cur);
      if (c == '\r' && s + 1 < end && s[1] == '\n') s++;
      *p = s + 1;
      return true;
    }
    cur.push_back(c); s++;
  }
}

// Worker count for the row-parallel paths: TM_NATIVE_THREADS, default
// hardware_concurrency (Spark local[*] analog — the ingest side of the
// framework may use every host core).
int tm_thread_count() {
  const char* env = getenv("TM_NATIVE_THREADS");
  if (env && *env) {
    int v = atoi(env);
    if (v >= 1) return v;
  }
  unsigned hc = std::thread::hardware_concurrency();
  return hc ? (int)hc : 1;
}

// Run fn(block_begin, block_end) over [0, n) split into contiguous
// blocks, one thread per block. Serial when a single worker suffices.
// min_per_block floors the per-thread work: spawning
// hardware_concurrency() threads for a 500-token hash batch costs more
// in create/join than the hashing itself (review r5) — callers whose
// unit of work is tiny pass a floor, callers whose unit is huge
// (a CSV shard = thousands of records) pass 1.
template <typename Fn>
void parallel_blocks(int64_t n, int64_t min_per_block, Fn fn) {
  int t = tm_thread_count();
  if (min_per_block > 1) {
    const int64_t max_threads = (n + min_per_block - 1) / min_per_block;
    if (t > max_threads) t = (int)(max_threads > 0 ? max_threads : 1);
  }
  if (t > n) t = (int)(n > 0 ? n : 1);
  if (t <= 1) {
    fn((int64_t)0, n);
    return;
  }
  std::vector<std::thread> workers;
  workers.reserve((size_t)t);
  const int64_t per = (n + t - 1) / t;
  for (int i = 0; i < t; ++i) {
    const int64_t b = (int64_t)i * per;
    const int64_t e = b + per < n ? b + per : n;
    if (b >= e) break;
    workers.emplace_back([=] { fn(b, e); });
  }
  for (auto& w : workers) w.join();
}

bool is_null_token(const std::string& s) {
  if (s.empty()) return true;
  static const char* kNulls[] = {"null", "na", "n/a", "none", "nan"};
  // Trim leading/trailing whitespace only (matches Python's s.strip();
  // interior whitespace must NOT be removed or 'n a' would parse as null
  // here but raise on the pure-Python row path).
  size_t b = 0, e = s.size();
  while (b < e && (s[b] == ' ' || s[b] == '\t')) b++;
  while (e > b && (s[e - 1] == ' ' || s[e - 1] == '\t')) e--;
  if (b == e) return true;
  std::string low;
  low.reserve(e - b);
  for (size_t i = b; i < e; i++)
    low.push_back((char)tolower((unsigned char)s[i]));
  for (const char* n : kNulls)
    if (low == n) return true;
  return false;
}

}  // namespace

extern "C" {

// Parse an in-memory CSV buffer (the file loader and the streaming
// block reader share this; `data` need not be NUL-terminated).
void* tm_csv_open_mem(const char* data_ptr, int64_t data_len, char delim,
                      int has_header) {
  auto* t = new CsvTable();
  const char* p = data_ptr;
  const char* end = p + data_len;
  std::vector<std::string> fields;
  if (has_header) {
    if (!parse_record(&p, end, delim, &fields)) { delete t; return nullptr; }
    t->header = fields;
  }

  // Phase 1 (serial): record-boundary scan. Quote state forces a serial
  // pass, but it is a single cheap byte loop; everything expensive
  // (field split, unquoting, arena builds) then parallelizes by record
  // range in phase 2.
  std::vector<const char*> starts;
  {
    const char* s = p;
    bool in_quotes = false;
    bool at_start = true;
    bool cell_start = true;
    while (s < end) {
      char c = *s;
      if (at_start) { starts.push_back(s); at_start = false; }
      if (in_quotes) {
        if (c == '"') {
          if (s + 1 < end && s[1] == '"') { s += 2; continue; }
          in_quotes = false;
        }
        s++;
        continue;
      }
      if (c == '"' && cell_start) { in_quotes = true; s++; continue; }
      cell_start = (c == delim);
      if (c == '\n' || c == '\r') {
        if (c == '\r' && s + 1 < end && s[1] == '\n') s++;
        s++;
        at_start = true;
        cell_start = true;
        continue;
      }
      s++;
    }
    starts.push_back(end);
  }
  const int64_t n_recs = (int64_t)starts.size() - 1;

  // Phase 2 (parallel): each worker parses a contiguous record range
  // into its own per-column arenas; ragged rows are padded per shard.
  struct Shard {
    std::vector<std::string> arenas;
    std::vector<std::vector<int64_t>> offs;
    int64_t rows = 0;
    bool trailing_blank = false;  // lone empty field at EOF: dropped
  };
  const int nt = tm_thread_count();
  const int n_shards = (int)(nt < (n_recs > 0 ? n_recs : 1)
                                 ? nt
                                 : (n_recs > 0 ? n_recs : 1));
  std::vector<Shard> shards((size_t)(n_shards > 0 ? n_shards : 1));
  const int64_t per = n_shards > 0 ? (n_recs + n_shards - 1) / n_shards : 0;
  parallel_blocks((int64_t)shards.size(), 1, [&](int64_t sb, int64_t se) {
    std::vector<std::string> f;
    for (int64_t si = sb; si < se; ++si) {
      Shard& sh = shards[(size_t)si];
      sh.offs.clear();
      const int64_t rb = si * per;
      const int64_t re = rb + per < n_recs ? rb + per : n_recs;
      auto ensure = [&](size_t n) {
        while (sh.arenas.size() < n) {
          sh.arenas.emplace_back();
          sh.offs.emplace_back();
          auto& o = sh.offs.back();
          // late-appearing column: pad the rows this shard already has
          for (int64_t r = 0; r <= sh.rows; ++r) o.push_back(0);
        }
      };
      for (int64_t r = rb; r < re; ++r) {
        const char* q = starts[(size_t)r];
        parse_record(&q, starts[(size_t)r + 1], delim, &f);
        if (f.size() == 1 && f[0].empty() && r + 1 == n_recs) {
          sh.trailing_blank = true;  // EOF blank line, matches old loop
          break;
        }
        ensure(f.size());
        for (size_t c = 0; c < sh.arenas.size(); ++c) {
          if (c < f.size()) sh.arenas[c] += f[c];
          sh.offs[c].push_back((int64_t)sh.arenas[c].size());
        }
        sh.rows++;
      }
    }
  });

  // Phase 3 (serial): ordered merge — memcpy-speed arena concatenation
  // with offset shifting; shards missing a column contribute empties.
  size_t ncols = t->header.size();
  for (const Shard& sh : shards)
    if (sh.arenas.size() > ncols) ncols = sh.arenas.size();
  t->arena.assign(ncols, std::string());
  t->offsets.assign(ncols, std::vector<int64_t>());
  for (size_t c = 0; c < ncols; ++c) t->offsets[c].push_back(0);
  for (const Shard& sh : shards) {
    for (size_t c = 0; c < ncols; ++c) {
      const int64_t base = (int64_t)t->arena[c].size();
      if (c < sh.arenas.size()) {
        t->arena[c] += sh.arenas[c];
        const auto& o = sh.offs[c];
        for (int64_t r = 1; r <= sh.rows; ++r)
          t->offsets[c].push_back(base + o[(size_t)r]);
      } else {
        for (int64_t r = 0; r < sh.rows; ++r)
          t->offsets[c].push_back(base);
      }
    }
    t->n_rows += sh.rows;
  }
  if (t->header.empty()) {
    char buf[32];
    for (size_t c = 0; c < ncols; ++c) {
      snprintf(buf, sizeof buf, "c%zu", c);
      t->header.push_back(buf);
    }
  }
  return t;
}

void* tm_csv_open(const char* path, char delim, int has_header) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  fseek(f, 0, SEEK_END);
  long size = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string data;
  data.resize((size_t)size);
  if (size > 0 && fread(&data[0], 1, (size_t)size, f) != (size_t)size) {
    fclose(f);
    return nullptr;
  }
  fclose(f);
  return tm_csv_open_mem(data.data(), (int64_t)data.size(), delim,
                         has_header);
}

// For the streaming block reader: byte offset (from `start`) of the
// first character AFTER the last COMPLETE record in the buffer, quote-
// aware. A block cut here never splits a record; the caller carries the
// tail into the next block. Returns 0 when no complete record ends in
// the buffer (caller must grow the block).
int64_t tm_csv_last_record_end(const char* data_ptr, int64_t data_len,
                               char delim) {
  bool in_quotes = false;
  bool cell_start = true;
  int64_t last_end = 0;
  for (int64_t i = 0; i < data_len; ++i) {
    char c = data_ptr[i];
    if (in_quotes) {
      if (c == '"') {
        if (i + 1 < data_len && data_ptr[i + 1] == '"') { i++; continue; }
        in_quotes = false;
      }
      continue;
    }
    if (c == '"' && cell_start) { in_quotes = true; continue; }
    cell_start = (c == delim);
    if (c == '\n') {
      last_end = i + 1;
      cell_start = true;
    } else if (c == '\r') {
      if (i + 1 >= data_len) {
        // trailing '\r' at the buffer edge may be half of a CRLF pair
        // split by the read boundary: treat as INCOMPLETE so the '\r'
        // carries into the next block instead of leaving a stray '\n'
        // that parses as a spurious all-null row (review r5, repro'd)
        break;
      }
      if (data_ptr[i + 1] == '\n') i++;
      last_end = i + 1;
      cell_start = true;
    }
  }
  return last_end;
}

int tm_csv_ncols(void* h) { return (int)((CsvTable*)h)->header.size(); }
int64_t tm_csv_nrows(void* h) { return ((CsvTable*)h)->n_rows; }

const char* tm_csv_header(void* h, int col) {
  auto* t = (CsvTable*)h;
  if (col < 0 || (size_t)col >= t->header.size()) return "";
  return t->header[col].c_str();
}

// Parse a column to float64; NaN for null tokens. Returns the number of
// cells that were neither numeric nor null (caller falls back if > 0).
int64_t tm_csv_numeric_col(void* h, int col, double* out) {
  auto* t = (CsvTable*)h;
  const std::string& a = t->arena[col];
  const auto& off = t->offsets[col];
  std::atomic<int64_t> bad_total{0};
  parallel_blocks(t->n_rows, 4096, [&](int64_t rb, int64_t re) {
    int64_t bad = 0;
    for (int64_t i = rb; i < re; ++i) {
      std::string cell =
          a.substr((size_t)off[i], (size_t)(off[i + 1] - off[i]));
      if (is_null_token(cell)) {
        out[i] = __builtin_nan("");
        continue;
      }
      // reject hex-float tokens ("0x10"): strtod accepts them but the
      // Python row path's float() does not — parity over permissiveness
      if (cell.find('x') != std::string::npos ||
          cell.find('X') != std::string::npos) {
        bad++;
        out[i] = __builtin_nan("");
        continue;
      }
      char* endp = nullptr;
      double v = strtod(cell.c_str(), &endp);
      while (endp && (*endp == ' ' || *endp == '\t')) endp++;
      if (!endp || *endp != '\0') {
        bad++;
        out[i] = __builtin_nan("");
      } else {
        out[i] = v;
      }
    }
    bad_total += bad;
  });
  return bad_total.load();
}

int64_t tm_csv_col_bytes(void* h, int col) {
  return (int64_t)((CsvTable*)h)->arena[col].size();
}

// Copy a string column's arena + n_rows+1 offsets.
void tm_csv_string_col(void* h, int col, char* buf, int64_t* offsets) {
  auto* t = (CsvTable*)h;
  const std::string& a = t->arena[col];
  memcpy(buf, a.data(), a.size());
  memcpy(offsets, t->offsets[col].data(),
         sizeof(int64_t) * (size_t)(t->n_rows + 1));
}

void tm_csv_close(void* h) { delete (CsvTable*)h; }

// ---------------------------------------------------------------------------
// MurmurHash3 x86 32-bit — bit-identical to ops/hashing.py murmur3_32.
// ---------------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

uint32_t tm_murmur3_32(const char* data, int64_t n, uint32_t seed) {
  const uint32_t c1 = 0xCC9E2D51, c2 = 0x1B873593;
  uint32_t h = seed;
  const int64_t rounded = n - (n % 4);
  for (int64_t i = 0; i < rounded; i += 4) {
    uint32_t k;
    memcpy(&k, data + i, 4);  // little-endian assumed (x86/ARM LE)
    k *= c1; k = rotl32(k, 15); k *= c2;
    h ^= k; h = rotl32(h, 13); h = h * 5 + 0xE6546B64;
  }
  uint32_t k = 0;
  const int64_t tail = n - rounded;
  if (tail >= 3) k ^= (uint32_t)(unsigned char)data[rounded + 2] << 16;
  if (tail >= 2) k ^= (uint32_t)(unsigned char)data[rounded + 1] << 8;
  if (tail >= 1) {
    k ^= (uint32_t)(unsigned char)data[rounded];
    k *= c1; k = rotl32(k, 15); k *= c2;
    h ^= k;
  }
  h ^= (uint32_t)n;
  h ^= h >> 16; h *= 0x85EBCA6B;
  h ^= h >> 13; h *= 0xC2B2AE35;
  h ^= h >> 16;
  return h;
}

// Hash a batch of tokens (concatenated buffer + offsets) into bins.
// Row-parallel: each token's output slot is independent.
void tm_murmur3_batch(const char* buf, const int64_t* offsets, int64_t n,
                      uint32_t seed, uint32_t n_bins, int32_t* out) {
  parallel_blocks(n, 4096, [&](int64_t b, int64_t e) {
    for (int64_t i = b; i < e; ++i) {
      uint32_t hv = tm_murmur3_32(buf + offsets[i],
                                  offsets[i + 1] - offsets[i], seed);
      out[i] = (int32_t)(hv % n_bins);
    }
  });
}

// Tokenize + hash-count a batch of TEXT CELLS (the hashing-trick
// vectorizer's hot loop: tokenize -> murmur3 -> scatter into bins, all
// without per-token Python objects). ASCII fast path only: tokens are
// maximal [A-Za-z0-9] runs lowercased, which is bit-identical to the
// Python tokenizer's [^\W_]+ regex for ASCII input. Any cell containing
// a non-ASCII byte is SKIPPED and flagged in `fallback` so the Python
// layer can process just those rows with the full Unicode regex —
// native speed for the common case, exact parity for the rest.
//
// out must be zeroed (n_rows, n_bins) float64, row-major.
// Row-parallel (VERDICT r4 item 5): each row owns its output slice, so
// blocks of rows thread cleanly; TM_NATIVE_THREADS caps the workers.
void tm_hash_count_rows(const char* buf, const int64_t* offsets,
                        int64_t n_rows, uint32_t seed, uint32_t n_bins,
                        int binary, int min_token_len, double* out,
                        uint8_t* fallback) {
  parallel_blocks(n_rows, 256, [&](int64_t rb, int64_t re) {
    std::string tok;
    for (int64_t i = rb; i < re; ++i) {
      const char* s = buf + offsets[i];
      const int64_t len = offsets[i + 1] - offsets[i];
      fallback[i] = 0;
      for (int64_t j = 0; j < len; ++j) {
        if ((unsigned char)s[j] >= 0x80) { fallback[i] = 1; break; }
      }
      if (fallback[i]) continue;
      double* row = out + (size_t)i * n_bins;
      tok.clear();
      for (int64_t j = 0; j <= len; ++j) {
        const unsigned char c = j < len ? (unsigned char)s[j] : 0;
        const bool alnum = (c >= '0' && c <= '9') ||
                           (c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z');
        if (alnum) {
          tok.push_back((c >= 'A' && c <= 'Z') ? (char)(c + 32) : (char)c);
          continue;
        }
        if ((int)tok.size() >= min_token_len && !tok.empty()) {
          uint32_t b = tm_murmur3_32(tok.data(), (int64_t)tok.size(), seed)
                       % n_bins;
          if (binary) row[b] = 1.0; else row[b] += 1.0;
        }
        tok.clear();
      }
    }
  });
}

}  // extern "C"
