"""Titanic survival — the canonical binary-classification hello world.

Reference: helloworld/src/main/scala/com/salesforce/hw/OpTitanicSimple
.scala: typed FeatureBuilders over the passenger schema, .transmogrify(),
sanityCheck, BinaryClassificationModelSelector with cross-validation,
then train/score/evaluate through the runner.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from transmogrifai_tpu import FeatureBuilder, models as M
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops.sanity_checker import SanityChecker
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.runner import OpParams, RunType, WorkflowRunner
from transmogrifai_tpu.workflow import Workflow

SCHEMA = {
    "id": ft.ID, "pclass": ft.PickList, "sex": ft.PickList, "age": ft.Real,
    "sibSp": ft.Integral, "parCh": ft.Integral, "fare": ft.Real,
    "cabin": ft.PickList, "embarked": ft.PickList, "survived": ft.RealNN,
}


def build_workflow():
    survived = (FeatureBuilder.of(ft.RealNN, "survived")
                .from_column().as_response())
    predictors = [FeatureBuilder.of(t, n).from_column().as_predictor()
                  for n, t in SCHEMA.items()
                  if n not in ("id", "survived")]
    features = transmogrify(predictors)
    checked = SanityChecker().set_input(survived, features).output
    prediction = M.BinaryClassificationModelSelector.with_cross_validation(
        n_folds=3,
        candidates=[
            ["LogisticRegression", {"regParam": [0.001, 0.01, 0.1],
                                    "elasticNetParam": [0.0, 0.5]}],
            ["RandomForestClassifier", None],
            ["GBTClassifier", None],
        ],
    ).set_input(survived, checked).output
    return Workflow([prediction])


def main(csv_path=None, out_dir="/tmp/op_titanic"):
    csv_path = csv_path or os.path.join(
        os.path.dirname(__file__), "data", "titanic.csv")
    reader = DataReaders.csv(csv_path, SCHEMA, key="id")
    runner = WorkflowRunner(build_workflow(), train_reader=reader,
                            score_reader=reader,
                            evaluator=Evaluators.binary_classification())
    params = OpParams(model_location=os.path.join(out_dir, "model"),
                      metrics_location=os.path.join(out_dir, "metrics"),
                      score_location=os.path.join(out_dir, "scores"))
    result = runner.run(RunType.TRAIN, params)
    print("best model:", result["bestModel"])
    print("train AuROC:", round(result["trainMetrics"]["AuROC"], 4))
    runner.run(RunType.SCORE, params)
    return result


if __name__ == "__main__":
    main(*sys.argv[1:2])
