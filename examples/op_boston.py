"""Boston housing — the regression hello world.

Reference: helloworld/src/main/scala/com/salesforce/hw/boston/OpBoston
.scala: numeric features transmogrified, RegressionModelSelector with
train/validation split.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from transmogrifai_tpu import FeatureBuilder, models as M
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.runner import OpParams, RunType, WorkflowRunner
from transmogrifai_tpu.workflow import Workflow

SCHEMA = {
    "crim": ft.Real, "zn": ft.Real, "indus": ft.Real, "chas": ft.Binary,
    "nox": ft.Real, "rm": ft.Real, "age": ft.Real, "dis": ft.Real,
    "rad": ft.Integral, "tax": ft.Real, "ptratio": ft.Real,
    "lstat": ft.Real, "medv": ft.RealNN,
}


def build_workflow():
    medv = FeatureBuilder.of(ft.RealNN, "medv").from_column().as_response()
    predictors = [FeatureBuilder.of(t, n).from_column().as_predictor()
                  for n, t in SCHEMA.items() if n != "medv"]
    features = transmogrify(predictors)
    prediction = M.RegressionModelSelector.with_train_validation_split(
        train_ratio=0.75,
        candidates=[
            ["LinearRegression", {"regParam": [0.001, 0.01, 0.1]}],
            ["RandomForestRegressor", None],
            ["GBTRegressor", None],
        ],
    ).set_input(medv, features).output
    return Workflow([prediction])


def main(csv_path=None, out_dir="/tmp/op_boston"):
    csv_path = csv_path or os.path.join(
        os.path.dirname(__file__), "data", "boston.csv")
    reader = DataReaders.csv(csv_path, SCHEMA)
    runner = WorkflowRunner(build_workflow(), train_reader=reader,
                            score_reader=reader,
                            evaluator=Evaluators.regression())
    params = OpParams(model_location=os.path.join(out_dir, "model"),
                      metrics_location=os.path.join(out_dir, "metrics"))
    result = runner.run(RunType.TRAIN, params)
    print("best model:", result["bestModel"])
    print("train R2:", round(result["trainMetrics"]["R2"], 4))
    return result


if __name__ == "__main__":
    main(*sys.argv[1:2])
