"""Generate the deterministic example datasets (committed as CSVs).

Reference: helloworld/ ships Titanic/Iris/Boston data files; this repo
cannot vendor those exact files, so seeded synthetic analogs with the
same schemas and learnable structure are generated once and committed.
Re-running this script reproduces them byte-for-byte.
"""
import csv
import os

import numpy as np

HERE = os.path.join(os.path.dirname(__file__), "data")


def make_titanic(path, n=891, seed=1912):
    rng = np.random.default_rng(seed)
    cols = ["id", "pclass", "sex", "age", "sibSp", "parCh", "fare",
            "cabin", "embarked", "survived"]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for i in range(n):
            pclass = int(rng.choice([1, 2, 3], p=[0.24, 0.21, 0.55]))
            sex = str(rng.choice(["male", "female"], p=[0.65, 0.35]))
            age = float(np.clip(rng.normal(38 - 4 * pclass, 14), 0.4, 80))
            age_s = "" if rng.random() < 0.2 else f"{age:.1f}"
            sibsp = int(rng.poisson(0.5))
            parch = int(rng.poisson(0.4))
            fare = float(np.round(rng.lognormal(4.2 - 0.9 * pclass, 0.6), 2))
            cabin = ("" if rng.random() < 0.77 else
                     f"{rng.choice(list('ABCDEF'))}{rng.integers(1, 130)}")
            embarked = str(rng.choice(["S", "C", "Q"], p=[0.72, 0.19, 0.09]))
            logit = (1.35 * (sex == "female") * 2 - 1.35
                     - 0.55 * (pclass - 2) - 0.018 * (age - 30)
                     - 0.25 * sibsp + 0.35 * (cabin != "")
                     + 0.004 * min(fare, 100))
            y = int(rng.random() < 1 / (1 + np.exp(-logit)))
            w.writerow([f"p{i}", pclass, sex, age_s, sibsp, parch,
                        f"{fare:.2f}", cabin, embarked, y])


def make_iris(path, n_per_class=50, seed=1936):
    rng = np.random.default_rng(seed)
    means = {  # sepal_len, sepal_wid, petal_len, petal_wid
        "setosa": (5.0, 3.4, 1.5, 0.25),
        "versicolor": (5.9, 2.8, 4.3, 1.3),
        "virginica": (6.6, 3.0, 5.6, 2.0),
    }
    sds = (0.35, 0.30, 0.35, 0.20)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["sepalLength", "sepalWidth", "petalLength", "petalWidth",
                    "irisClass"])
        for cls, mu in means.items():
            for _ in range(n_per_class):
                vals = [max(0.1, rng.normal(m, s)) for m, s in zip(mu, sds)]
                w.writerow([f"{v:.1f}" for v in vals] + [cls])


def make_boston(path, n=506, seed=1978):
    rng = np.random.default_rng(seed)
    cols = ["crim", "zn", "indus", "chas", "nox", "rm", "age", "dis",
            "rad", "tax", "ptratio", "lstat", "medv"]
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for _ in range(n):
            crim = float(rng.lognormal(-1.5, 1.8))
            zn = float(rng.choice([0, 12.5, 25, 80], p=[0.7, 0.1, 0.1, 0.1]))
            indus = float(rng.uniform(0.5, 27))
            chas = int(rng.random() < 0.07)
            nox = float(rng.uniform(0.38, 0.87))
            rm = float(rng.normal(6.3, 0.7))
            age = float(rng.uniform(3, 100))
            dis = float(rng.lognormal(1.2, 0.5))
            rad = int(rng.choice([1, 2, 3, 4, 5, 6, 7, 8, 24]))
            tax = float(rng.uniform(190, 711))
            ptratio = float(rng.uniform(12.6, 22))
            lstat = float(rng.lognormal(2.4, 0.5))
            medv = (36 + 5.2 * (rm - 6.3) - 0.62 * min(lstat, 38)
                    - 0.22 * crim - 18 * (nox - 0.55) + 2.8 * chas
                    - 0.30 * ptratio + rng.normal(0, 2.5))
            medv = float(np.clip(medv, 5, 50))
            w.writerow([f"{crim:.4f}", zn, f"{indus:.2f}", chas,
                        f"{nox:.3f}", f"{rm:.3f}", f"{age:.1f}",
                        f"{dis:.3f}", rad, f"{tax:.0f}", f"{ptratio:.1f}",
                        f"{lstat:.2f}", f"{medv:.2f}"])


if __name__ == "__main__":
    os.makedirs(HERE, exist_ok=True)
    make_titanic(os.path.join(HERE, "titanic.csv"))
    make_iris(os.path.join(HERE, "iris.csv"))
    make_boston(os.path.join(HERE, "boston.csv"))
    print("wrote", sorted(os.listdir(HERE)))
