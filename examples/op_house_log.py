"""Log-scaled-label regression with descaled serving + sensitive columns.

The reference pattern this demonstrates (ScalerTransformer.scala +
PredictionDescalerTransformer.scala + 0.7 sensitive feature detection):
house prices are log-normal, so the selector trains on log(price) and
predictions descale to dollars at serving time; the seller-name column
is detected as human names and REMOVED from the feature vector before
any model sees it — the verdict lands in ModelInsights'
sensitiveFeatureInformation.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

from transmogrifai_tpu import FeatureBuilder, models as M
from transmogrifai_tpu.dataset import Dataset
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops import PredictionDescaler, ScalerTransformer
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.ops.vectorizers import (SmartTextVectorizer,
                                               VectorsCombiner)
from transmogrifai_tpu.workflow import Workflow

N_ROWS = 400


def make_dataset(n=N_ROWS, seed=0):
    rng = np.random.default_rng(seed)
    sqft = rng.uniform(40, 400, n)
    rooms = rng.integers(1, 8, n).astype(float)
    age = rng.uniform(0, 80, n)
    first = ["James", "Mary", "Robert", "Elena", "Carlos", "Yuki",
             "Omar", "Linda"]
    last = ["Smith", "Garcia", "Lee", "Brown", "Davis", "Wilson"]
    seller = [f"{first[i % 8]} {last[i % 6]}" for i in range(n)]
    price = np.exp(10.0 + 0.004 * sqft + 0.08 * rooms - 0.003 * age
                   + 0.08 * rng.normal(size=n))
    return Dataset(
        {"sqft": sqft, "rooms": rooms, "age": age,
         "seller": np.asarray(seller, dtype=object), "price": price},
        {"sqft": ft.Real, "rooms": ft.Integral, "age": ft.Real,
         "seller": ft.Text, "price": ft.RealNN})


def build_workflow():
    price = FeatureBuilder.of(ft.RealNN, "price").from_column() \
        .as_response()
    nums = [FeatureBuilder.of(t, n).from_column().as_predictor()
            for n, t in (("sqft", ft.Real), ("rooms", ft.Integral),
                         ("age", ft.Real))]
    seller = FeatureBuilder.of(ft.Text, "seller").from_column() \
        .as_predictor()

    log_price = ScalerTransformer(scaling_type="log") \
        .set_input(price).output                      # stays RealNN+response
    seller_vec = SmartTextVectorizer(sensitive_feature_mode="remove") \
        .set_input(seller).output                     # 0 columns if names
    fv = VectorsCombiner().set_input(
        seller_vec, transmogrify(nums)).output
    pred = M.RegressionModelSelector.with_train_validation_split(
        train_ratio=0.75,
        candidates=[["LinearRegression", {"regParam": [0.001, 0.01]}],
                    ["GBTRegressor", None]],
    ).set_input(log_price, fv).output
    served = PredictionDescaler().set_input(pred, log_price).output
    return Workflow([served]), served


def main():
    ds = make_dataset()
    wf, served = build_workflow()
    model = wf.train(ds)
    out = np.asarray(model.score(ds).column(served.name), np.float64)
    y = np.asarray(ds.column("price"), np.float64)
    rel = float(np.median(np.abs(out - y) / y))
    sens = model.model_insights().get("sensitiveFeatureInformation", [])
    print(f"median relative error (dollars): {rel:.3f}")
    print(f"sensitive columns: {sens}")
    return rel, sens


if __name__ == "__main__":
    main()
