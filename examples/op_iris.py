"""Iris — the multiclass hello world.

Reference: helloworld/src/main/scala/com/salesforce/hw/iris/OpIris.scala:
string label indexed to RealNN, numeric features transmogrified,
MultiClassificationModelSelector with cross-validation.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from transmogrifai_tpu import FeatureBuilder, models as M
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.ops.parsers import StringIndexer
from transmogrifai_tpu.ops.transmogrifier import transmogrify
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.runner import OpParams, RunType, WorkflowRunner
from transmogrifai_tpu.workflow import Workflow

SCHEMA = {
    "sepalLength": ft.Real, "sepalWidth": ft.Real,
    "petalLength": ft.Real, "petalWidth": ft.Real,
    "irisClass": ft.RealNN,  # indexed upstream of the workflow
}


def read_iris(csv_path):
    """Index the string class label to 0..2 (OpIris uses OpStringIndexer)."""
    raw_schema = dict(SCHEMA, irisClass=ft.PickList)
    reader = DataReaders.csv(csv_path, raw_schema)
    records = reader.read()
    labels = sorted({r["irisClass"] for r in records})
    for r in records:
        r["irisClass"] = float(labels.index(r["irisClass"]))
    return DataReaders.simple(records), labels


def build_workflow():
    label = (FeatureBuilder.of(ft.RealNN, "irisClass")
             .from_column().as_response())
    predictors = [FeatureBuilder.of(t, n).from_column().as_predictor()
                  for n, t in SCHEMA.items() if n != "irisClass"]
    features = transmogrify(predictors)
    prediction = M.MultiClassificationModelSelector.with_cross_validation(
        n_folds=3,
        candidates=[
            ["LogisticRegression", {"regParam": [0.01, 0.1]}],
            ["RandomForestClassifier", None],
        ],
    ).set_input(label, features).output
    return Workflow([prediction])


def main(csv_path=None, out_dir="/tmp/op_iris"):
    csv_path = csv_path or os.path.join(
        os.path.dirname(__file__), "data", "iris.csv")
    reader, labels = read_iris(csv_path)
    runner = WorkflowRunner(build_workflow(), train_reader=reader,
                            score_reader=reader,
                            evaluator=Evaluators.multi_classification())
    params = OpParams(model_location=os.path.join(out_dir, "model"),
                      metrics_location=os.path.join(out_dir, "metrics"))
    result = runner.run(RunType.TRAIN, params)
    print("classes:", labels)
    print("best model:", result["bestModel"])
    print("train error:", round(result["trainMetrics"]["Error"], 4))
    return result


if __name__ == "__main__":
    main(*sys.argv[1:2])
