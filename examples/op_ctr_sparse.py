"""Criteo-style CTR — the sparse hashed-feature hello world.

Reference scope: the reference's large-scale path is
OPCollectionHashingVectorizer -> OpLogisticRegression on Spark sparse
vectors (SURVEY §7 step 7 "Criteo scale"). TPU-native equivalent: raw
categorical columns hash to a (n, K) int32 index matrix
(SparseHashingVectorizer — no dense (n, buckets) block ever exists),
numerics vectorize densely, and the SparseModelSelector sweeps the
three CTR families — minibatch Adagrad-LR, FTRL-Proximal, and a
hashed factorization machine — as vmapped programs over the
optimizer-state axis, with the sweep, the winner's refit, and the
evaluation all streaming the same chunk iterator (device residency
bounded by chunk_rows, never the dataset).

Run: python examples/op_ctr_sparse.py [n_rows] [out_dir]
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from transmogrifai_tpu import FeatureBuilder
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.features import types as ft
from transmogrifai_tpu.models.sparse import SparseModelSelector
from transmogrifai_tpu.ops.transmogrifier import transmogrify_sparse
from transmogrifai_tpu.readers import DataReaders
from transmogrifai_tpu.runner import OpParams, RunType, WorkflowRunner
from transmogrifai_tpu.workflow import Workflow

N_CAT, N_NUM = 8, 4
BUCKETS = 1 << 18
# hashed-field order: transmogrify_sparse preserves this input order,
# so fieldContributions is positional over the same list
CAT_NAMES = ["device", "slot", "campaign"] + [f"cat{j}"
                                              for j in range(N_CAT - 3)]


def make_records(n_rows: int, seed: int = 0):
    """Synthetic CTR events: device/slot/campaign-style categoricals (two
    carry signal) plus numeric counters."""
    rng = np.random.default_rng(seed)
    device = rng.choice(["ios", "android", "web"], n_rows, p=[.3, .5, .2])
    slot = rng.integers(0, 400, n_rows)
    campaign = rng.integers(0, 3000, n_rows)
    noise_cats = rng.integers(0, 100_000, size=(n_rows, N_CAT - 3))
    nums = rng.normal(size=(n_rows, N_NUM)).astype(np.float64)
    logit = (np.where(device == "ios", 0.8, np.where(device == "web", -0.6,
                                                     0.1))
             + np.where(slot % 7 < 2, 0.9, -0.3) + 0.5 * nums[:, 0])
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-logit))).astype(float)
    recs = []
    for i in range(n_rows):
        r = {"device": str(device[i]), "slot": f"s{slot[i]}",
             "campaign": f"c{campaign[i]}", "click": float(y[i])}
        for j in range(N_CAT - 3):
            r[f"cat{j}"] = f"v{noise_cats[i, j]}"
        for j in range(N_NUM):
            r[f"num{j}"] = float(nums[i, j])
        recs.append(r)
    return recs


def build_workflow(buckets: int = BUCKETS, chunk_rows: int = 1_000_000):
    """The FRONT-DOOR Criteo flow: `transmogrify_sparse` routes the
    categorical columns into one shared hashed space (SparseIndices) and
    the numerics into the dense vector; `SparseModelSelector` grid-
    validates the hashed LR as one vmapped program and streaming-refits
    the winner (io/stream.py multi-epoch prefetch)."""
    click = FeatureBuilder.of(ft.RealNN, "click").from_column().as_response()
    cats = [FeatureBuilder.of(ft.PickList, c).from_column().as_predictor()
            for c in CAT_NAMES]
    nums = [FeatureBuilder.of(ft.Real, f"num{j}").from_column().as_predictor()
            for j in range(N_NUM)]
    hashed, dense = transmogrify_sparse(cats + nums, num_buckets=buckets)
    pred = SparseModelSelector(
        num_buckets=buckets, n_folds=2, epochs=1, refit_epochs=2,
        batch_size=4096, chunk_rows=chunk_rows,
        # all three CTR families compete
        grid=[{"family": "adagrad", "lr": lr, "l2": 0.0}
              for lr in (0.05, 0.1)]
            + [{"family": "ftrl", "alpha": 0.1, "l1": 0.0},
               {"family": "fm", "lr": 0.05, "l2": 0.0}],
    ).set_input(click, hashed, dense).output
    return Workflow([pred]), click


def main(n_rows: int = 20_000, out_dir: str = "/tmp/op_ctr"):
    recs = make_records(n_rows)
    reader = DataReaders.simple(recs)
    wf, click = build_workflow()
    runner = WorkflowRunner(
        wf, train_reader=reader, score_reader=reader,
        evaluator=Evaluators.binary_classification())
    os.makedirs(out_dir, exist_ok=True)
    params = OpParams(model_location=os.path.join(out_dir, "model"),
                      metrics_location=os.path.join(out_dir, "metrics"),
                      response="click")
    train_res = runner.run(RunType.TRAIN, params)
    eval_res = runner.run(RunType.EVALUATE, params)
    metrics = eval_res["metrics"]
    # field-level insight: which hashed fields carry the model's weight
    contrib = train_res.get("fieldContributions")
    top_fields = None
    if contrib:
        ranked = sorted(zip(CAT_NAMES, contrib), key=lambda t: -t[1])
        top_fields = [f for f, _ in ranked[:3]]
    print({"AuROC": round(metrics["AuROC"], 4), "rows": n_rows,
           "buckets": BUCKETS, "bestModel": train_res["bestModel"],
           "topFields": top_fields})
    return metrics


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 20_000
    out = sys.argv[2] if len(sys.argv) > 2 else "/tmp/op_ctr"
    main(n, out)
