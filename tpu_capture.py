"""Opportunistic TPU bench-capture daemon (VERDICT r3, next-round item 1).

The accelerator tunnel in this sandbox is intermittent: it hangs inside
device calls (no error) and can stay dead for hours, which cost rounds 2
and 3 every hardware number. This daemon turns capture from an event
into a background loop:

  probe (subprocess, hard timeout) -> if alive, run the single
  highest-priority UNMEASURED bench section (bench.py --section NAME,
  subprocess, hard timeout) -> record to BENCH_CAPTURE.json -> re-probe.

Every probe and section outcome is appended to PROBE_LOG.txt, so even a
round where the tunnel never comes up leaves a verifiable attempt
history. Section priority follows the verdict: kernel decision first
(hist_kernels), then grid/fold speedups, then e2e latency/throughput.
"""
from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
LOG = os.path.join(REPO, "PROBE_LOG.txt")
STATE = os.path.join(REPO, "BENCH_CAPTURE.json")
PRIORITY = [
    "hist_kernels",      # decides TM_PALLAS default (v3 kernel vs XLA)
    "gbt_grid",          # folded_speedup_vs_vmap on real silicon
    "lr_grid",           # bf16 vs round-1's 499.41 fits/s/chip
    "sweep_scaling",     # 1/2/4/8-chip per-chip efficiency of the fused
    #                      sweep (ROADMAP item 1 acceptance: >=0.7x at 8)
    "kernel_autotune",   # config sweep + learned cost model + the
    #                      never-slower guard (ISSUE 12: >=5x
    #                      hist_kernels target rides hist_kernels above)
    "fused_scoring",     # batch + row-fn latency
    "fused_stream",      # bucketed serving stream vs per-shape-jit tax
    "engine_latency",    # micro-batching engine vs serialized requests
    "telemetry_overhead",  # tracing-on vs -off engine p99 (<= 1.05 bar)
    "request_overhead",  # host us/request by segment, legacy vs fast
    #                      dispatcher (>= 1.5x ceiling bar); numpy-only
    #                      — runs fine even when the tunnel is dead
    "fleet_failover",    # kill-1-of-4 p99 + error rate under Poisson load
    "elastic_load",      # autoscaler vs static-N: p99 + shed rate on
    #                      step/spike/diurnal + scale-up-to-serving wall
    "multi_model_load",  # Zipf(1.1) 100-model catalog: cross-model
    #                      co-batch vs per-model serial dispatch at
    #                      equal p99 + per-tenant-tier p99
    "fused_serving",     # device-side fused family kernel vs Python
    #                      co-batch A/B + serving-kernel autotune sweep
    #                      (trains the TM_AUTOTUNE_SERVING_MODEL artifact)
    "cross_host_load",   # N socket workers vs 1-process inproc fleet:
    #                      aggregate req/s + wire-overhead p99 budget
    #                      gate; dispatch-emulated, runs tunnel-dead
    "gray_failure",      # one-replica partition: hedged vs unhedged
    #                      p99 + ejection rescue, and the retry-budget
    #                      amplification gate under full-fleet response
    #                      corruption; dispatch-emulated, runs
    #                      tunnel-dead
    "drift_loop",        # continuum: detect/retrain/rollback walls +
    #                      shadow-scoring p99 overhead (<= 1.10 bar)
    "ctr_10m_streaming", # HBM-streaming device throughput
    "workflow_train",    # parallel DAG executor vs the seed serial train
    "train_resume",      # checkpoint overhead + resume-from-50% wall clock
    "titanic_e2e",
    "ctr_front_door",
    "ft_transformer",
    "hist_block_tune",   # block_n sweep: the kernel's next headroom
]
PROBE_TIMEOUT_S = 95
SECTION_TIMEOUT_S = 1100
# heavy sections (many compiles / 10M host-side rows on this 1-core box)
# get a longer leash — a timeout kill wastes a whole alive-window slot
SECTION_TIMEOUT_OVERRIDES = {
    "ctr_10m_streaming": 2400,
    "fused_scoring": 1800,
    "titanic_e2e": 1800,
    "workflow_train": 2400,   # feature trains + 2 automl warmups +
                              # min-of-2 seed/fused + parity train
    "train_resume": 1800,     # warmup + 6 timed trains + crash/resume
}
DEAD_SLEEP_S = 300       # ~6.6 min/cycle incl. the 95s hang: round-3's
                         # windows were short; probe often, probes are cheap
ALL_DONE_SLEEP_S = 3600  # everything captured: hourly re-confirm probe


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def log(msg: str) -> None:
    line = f"{_now()} {msg}"
    print(line, flush=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def load_state() -> dict:
    try:
        with open(STATE) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def save_state(st: dict) -> None:
    tmp = STATE + ".tmp"
    with open(tmp, "w") as f:
        json.dump(st, f, indent=1, default=float)
        f.write("\n")
    os.replace(tmp, STATE)


def probe() -> tuple:
    """(alive, info_line). Hard-timeout subprocess; a hang is 'dead'."""
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tpu_probe.py")],
            capture_output=True, text=True, timeout=PROBE_TIMEOUT_S,
            cwd=REPO)
        out = r.stdout.strip().splitlines()
        return r.returncode == 0, (out[-1] if out else r.stderr[-120:])
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{PROBE_TIMEOUT_S}s (tunnel dead)"
    except Exception as e:  # noqa: BLE001
        return False, f"probe error: {e}"


def _section_timeout(name: str) -> int:
    return SECTION_TIMEOUT_OVERRIDES.get(name, SECTION_TIMEOUT_S)


def run_section(name: str) -> dict:
    timeout_s = _section_timeout(name)
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--section", name],
            capture_output=True, text=True, timeout=timeout_s,
            cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s}s"}
    if r.returncode != 0:
        return {"error": f"rc={r.returncode}: {r.stderr[-400:]}"}
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return {"error": f"unparseable output: {r.stdout[-200:]}"}


def next_section(st: dict):
    """Unattempted sections first (priority order), THEN the
    least-recently-attempted failed one — a section that
    deterministically times out must not starve the others of an
    alive-window, either before its first attempt or on retries."""
    for name in PRIORITY:
        if st.get(name) is None:
            return name
    failed = [n for n in PRIORITY if not st[n].get("ok")]
    if failed:
        return min(failed, key=lambda n: st[n].get("at", ""))
    return None


def main() -> None:
    log(f"capture daemon start (pid {os.getpid()})")
    while True:
        st = load_state()
        name = next_section(st)
        alive, info = probe()
        log(f"probe alive={alive} {info}")
        if not alive:
            time.sleep(DEAD_SLEEP_S)
            continue
        if name is None:
            log("all priority sections captured")
            time.sleep(ALL_DONE_SLEEP_S)
            continue
        log(f"running section {name} (timeout {_section_timeout(name)}s)")
        t0 = time.monotonic()
        res = run_section(name)
        ok = isinstance(res, dict) and "error" not in res
        st = load_state()
        st[name] = {"ok": ok, "at": _now(),
                    "seconds": round(time.monotonic() - t0, 1),
                    "result": res}
        save_state(st)
        log(f"section {name} ok={ok} in {st[name]['seconds']}s"
            + ("" if ok else f" ({str(res.get('error'))[:160]})"))
        # loop back to the top: the next iteration re-probes before
        # picking another section, so a hang-killed section (the usual
        # sign the tunnel died mid-capture) falls through to the
        # dead-sleep path instead of burning another timeout


if __name__ == "__main__":
    main()
